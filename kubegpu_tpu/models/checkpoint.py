"""Workload checkpoint/resume via Orbax (SURVEY.md §5.4).

The framework's durable state lives in API-server annotations; WORKLOAD
state (params/optimizer) is the pods' business — this module is the pods'
side of that contract, completing the elastic-recovery story: pod dies →
controller restarts it → the pod re-schedules through the extender → the
worker resumes from its last checkpoint instead of step 0.

Orbax is sharding-native: arrays are saved with their shardings and
restored into whatever shardings the (possibly different) restart mesh
placed on the template state, so a gang rescheduled onto a *different*
ICI-contiguous sub-mesh resumes cleanly.  Multi-host gangs need a path all
workers share (GCS/NFS in production; save/restore are collective when
``jax.distributed`` is up).

Only the array subtree of TrainState travels — ``apply_fn``/``tx`` are
code, which belongs to the image, not the checkpoint.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import orbax.checkpoint as ocp

from kubegpu_tpu.models.train import TrainState

log = logging.getLogger(__name__)


def _arrays_of(state: TrainState) -> dict:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


def make_manager(ckpt_dir: str, max_to_keep: int = 3) -> ocp.CheckpointManager:
    """CheckpointManager with step numbering + retention (keeps the last
    ``max_to_keep``); directory is created on first save."""
    return ocp.CheckpointManager(
        ckpt_dir,
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
    )


def save_checkpoint(mgr: ocp.CheckpointManager, state: TrainState) -> int:
    """Save the state's array subtree at its current step; returns the step."""
    step = int(jax.device_get(state.step))
    mgr.save(step, args=ocp.args.StandardSave(_arrays_of(state)))
    return step


def latest_step(mgr: ocp.CheckpointManager) -> Optional[int]:
    return mgr.latest_step()


def restore_checkpoint(
    mgr: ocp.CheckpointManager, template: TrainState, step: Optional[int] = None
) -> Optional[TrainState]:
    """Restore into the TEMPLATE's shardings (build the template exactly as
    for a fresh run — model init + placement — so restored arrays land
    sharded the same way); returns None when no checkpoint exists."""
    step = mgr.latest_step() if step is None else step
    if step is None:
        return None
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
        if hasattr(a, "sharding")
        else a,
        _arrays_of(template),
    )
    restored = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    log.info("restored checkpoint step=%d", step)
    return template.replace(
        step=restored["step"],
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
    )
