"""Train steps: jitted, sharded, donate-friendly — the compute payload.

One pattern for both workloads (the scaling-book recipe): build a Mesh,
place the state/batch with NamedShardings, jit the step, let GSPMD insert
collectives.  Nothing here knows about hosts or NCCL-style process groups —
multi-host is jax.distributed (brought up from the env the CRI shim
injected) plus the same jit.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from kubegpu_tpu.parallel.sharding import (
    MODEL_AXIS,
    MOE_EP_RULES,
    MOE_EP_TP_RULES,
    TRANSFORMER_TP_RULES,
    batch_sharding,
    current_mesh,
    param_shardings,
    replicated,
)


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any          # {} for stateless models
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads, new_batch_stats=None):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
            opt_state=new_opt,
        )


def _make_init(model, tx):
    def _init(rng, x):
        variables = model.init(rng, x)
        params = variables["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
            apply_fn=model.apply,
            tx=tx,
        )

    return _init


def create_train_state(
    model, rng, sample_input, tx: Optional[optax.GradientTransformation] = None
) -> TrainState:
    tx = tx or optax.sgd(0.1, momentum=0.9, nesterov=True)

    # jit the whole init: eager flax init dispatches one tiny op per
    # parameter, which is pathologically slow on remote/tunnelled
    # accelerators (measured ~15x slower than one compiled program for
    # ResNet-50 on a tunnelled v5e chip).  sample_input is a traced
    # argument, not a closure capture — baking a real batch in as a
    # constant would bloat the program and key caches on its values.
    # (Init with the SMALLEST batch that traces — param shapes are
    # batch-independent and the init program compiles ~2x faster at b1;
    # bench.py's cold probe relies on this.)
    return jax.jit(_make_init(model, tx))(rng, sample_input)


def train_state_template(
    model, rng, sample_input, tx: Optional[optax.GradientTransformation] = None
) -> TrainState:
    """Abstract TrainState (ShapeDtypeStruct leaves — NO device memory,
    no compile): the restore template for serving paths that load a
    training checkpoint without ever materializing a fresh init or its
    optimizer state on device (`worker --model decode`)."""
    tx = tx or optax.sgd(0.1, momentum=0.9, nesterov=True)
    return jax.eval_shape(_make_init(model, tx), rng, sample_input)


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))


# ---------------------------------------------------------------------------
# ResNet (image classification, DP)
# ---------------------------------------------------------------------------

def resnet_loss(state: TrainState, params, batch_stats, images, labels):
    logits, mutated = state.apply_fn(
        {"params": params, "batch_stats": batch_stats},
        images,
        train=True,
        mutable=["batch_stats"],
    )
    return cross_entropy(logits, labels), mutated["batch_stats"]


def make_resnet_train_step(mesh: Mesh, donate: bool = True):
    """Jitted DP step: state replicated, batch sharded over "data"."""

    def step(state: TrainState, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            lambda p: resnet_loss(state, p, state.batch_stats, images, labels),
            has_aux=True,
        )(state.params)
        return state.apply_gradients(grads, new_batch_stats=new_stats), loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def place_resnet(state: TrainState, batch, mesh: Mesh):
    """Device placement: replicate state, shard the batch."""
    state = jax.device_put(state, replicated(mesh))
    images, labels = batch
    images = jax.device_put(images, batch_sharding(mesh))
    labels = jax.device_put(labels, batch_sharding(mesh))
    return state, images, labels


# ---------------------------------------------------------------------------
# Transformer LM (DP x TP + SP)
# ---------------------------------------------------------------------------

def lm_loss(state: TrainState, params, tokens):
    logits = state.apply_fn({"params": params}, tokens[:, :-1])
    return cross_entropy(logits, tokens[:, 1:])


def make_lm_train_step(mesh: Mesh, donate: bool = True):
    def step(state: TrainState, tokens):
        # context active during tracing so the model's sequence-parallel
        # sharding constraints resolve against this mesh
        with current_mesh(mesh):
            loss, grads = jax.value_and_grad(lambda p: lm_loss(state, p, tokens))(
                state.params
            )
            return state.apply_gradients(grads), loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())


# ---------------------------------------------------------------------------
# Draft distillation (speculative decoding)
# ---------------------------------------------------------------------------
#
# The speculative batcher's accept probability at one position is
# sum_x min(p(x), q(x)) = 1 - TV(p, q): the draft's job is not to be a
# good LM, it is to MATCH the target's conditionals on the traffic the
# fleet actually decodes.  So the recipe distills on target ROLLOUTS
# (prompts continued by the serving model — sampled or greedy, the
# distribution acceptance is measured against) with the target's own
# logits as the label, not corpus text: forward KL(p || q) at every
# position directly minimizes an upper bound on the rejection rate
# (Pinsker: TV <= sqrt(KL/2)).  A small hard-label term keeps the
# draft's argmax pinned to the teacher's on high-confidence positions,
# which is what the GREEDY lane's accept rate measures.

def draft_distill_loss(state: TrainState, params, tokens, teacher_logits,
                       temperature: float = 1.0,
                       hard_weight: float = 0.1):
    """Distillation loss for a draft on teacher rollouts.

    ``tokens``: (b, L+1) rollout token ids (prompt + continuation);
    ``teacher_logits``: (b, L, V) the target model's logits at each
    next-token position, captured during the rollout (or recomputed by
    the ``make_draft_distill_step`` wrapper).  Returns
    ``KL(teacher || draft) * T^2 + hard_weight * CE(draft, tokens)``.
    """
    logits = state.apply_fn({"params": params}, tokens[:, :-1])
    t = float(temperature)
    t_logp = jax.nn.log_softmax(
        jax.lax.stop_gradient(teacher_logits).astype(jnp.float32) / t,
        axis=-1,
    )
    s_logp = jax.nn.log_softmax(logits.astype(jnp.float32) / t, axis=-1)
    # forward KL: mass where the TEACHER puts it — exactly the measure
    # the rejection sampler scores the draft against
    kl = jnp.mean(
        jnp.sum(jnp.exp(t_logp) * (t_logp - s_logp), axis=-1)
    ) * t * t
    return kl + hard_weight * cross_entropy(logits, tokens[:, 1:])


def make_draft_distill_step(mesh: Mesh, teacher_apply_fn,
                            temperature: float = 1.0,
                            hard_weight: float = 0.1,
                            donate: bool = True):
    """Jitted distillation step: ``step(state, teacher_params, tokens)``
    -> ``(state', loss)``.  The teacher forward runs inside the step
    under ``stop_gradient`` (frozen), so callers feed raw rollout
    tokens — no (b, L, V) teacher-logit tensors ride the host loop.
    ``teacher_apply_fn`` is the target model's ``.apply``; the draft's
    is already bound in ``state.apply_fn``."""

    def step(state: TrainState, teacher_params, tokens):
        with current_mesh(mesh):
            t_logits = jax.lax.stop_gradient(
                teacher_apply_fn({"params": teacher_params}, tokens[:, :-1])
            )
            loss, grads = jax.value_and_grad(
                lambda p: draft_distill_loss(
                    state, p, tokens, t_logits,
                    temperature=temperature, hard_weight=hard_weight,
                )
            )(state.params)
            return state.apply_gradients(grads), loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())


# ---------------------------------------------------------------------------
# MoE transformer LM (DP x EP)
# ---------------------------------------------------------------------------

def moe_loss(state: TrainState, params, tokens, aux_weight: float):
    logits, mutated = state.apply_fn(
        {"params": params}, tokens[:, :-1], mutable=["intermediates"]
    )
    # each MoEMLP sowed one aux_loss scalar; select by name so unrelated
    # diagnostic sows never leak into the loss; mean over layers keeps the
    # weight independent of depth
    flat, _ = jax.tree_util.tree_flatten_with_path(
        mutated.get("intermediates", {})
    )
    aux_leaves = [
        leaf
        for path, leaf in flat
        if any(getattr(k, "key", None) == "aux_loss" for k in path)
    ]
    aux = (
        sum(jnp.mean(a) for a in aux_leaves) / max(len(aux_leaves), 1)
        if aux_leaves
        else jnp.zeros(())
    )
    return cross_entropy(logits, tokens[:, 1:]) + aux_weight * aux, aux


def make_moe_train_step(mesh: Mesh, aux_weight: float = 0.01, donate: bool = True):
    """Jitted DP x EP step: expert params sharded over "expert" per
    MOE_EP_RULES, batch over "data"; the Switch aux loss keeps the router
    balanced (returned as the step's second metric)."""

    def step(state: TrainState, tokens):
        with current_mesh(mesh):
            (loss, aux), grads = jax.value_and_grad(
                lambda p: moe_loss(state, p, tokens, aux_weight), has_aux=True
            )(state.params)
            return state.apply_gradients(grads), loss, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def place_moe(state: TrainState, tokens, mesh: Mesh):
    """EP placement (params AND mirrored optimizer moments); batch sharded
    over "data".  A mesh carrying a "model" axis takes the EP x TP rules:
    expert FFNs Megatron-sharded inside their expert shard, attention/
    embed/head TP-sharded."""
    rules = MOE_EP_TP_RULES if MODEL_AXIS in mesh.axis_names else MOE_EP_RULES
    state = jax.device_put(state, state_shardings(state, mesh, rules))
    tokens = jax.device_put(tokens, batch_sharding(mesh))
    return state, tokens


def state_shardings(state: TrainState, mesh: Mesh, rules) -> TrainState:
    """NamedShardings for every leaf of the train state by path rules —
    just param_shardings over the whole state pytree: optimizer-moment
    trees mirror the param tree, so their leaf paths end in the same
    ``.../q_proj/kernel`` suffix and the SAME rules shard them consistently
    with their params (the standard requirement for TP)."""
    return param_shardings(state, mesh, rules)


def place_lm(state: TrainState, tokens, mesh: Mesh):
    """TP placement per TRANSFORMER_TP_RULES (params AND mirrored optimizer
    moments); batch sharded over "data"."""
    state = jax.device_put(state, state_shardings(state, mesh, TRANSFORMER_TP_RULES))
    tokens = jax.device_put(tokens, batch_sharding(mesh))
    return state, tokens


def place_cp_lm(state: TrainState, tokens, mesh: Mesh):
    """Context-parallel placement (mesh axes ("data", "seq")): params
    replicated, tokens batch-sharded.  The ACTIVATIONS get their (data,
    seq, ...) layout from the model's constrain_ctx_sharded right after
    the embed — the raw token array is a few bytes per row and its length
    (seq+1 before the shift) need not divide the seq axis, so sharding it
    would only add a constraint the data can't always satisfy.  Works on
    pure-CP meshes (no "data" axis) too — tokens just replicate, matching
    the model layer's batch_axis=None branch."""
    from jax.sharding import NamedSharding, PartitionSpec

    from kubegpu_tpu.parallel.sharding import batch_axes

    state = jax.device_put(state, replicated(mesh))
    tokens = jax.device_put(
        tokens, NamedSharding(mesh, PartitionSpec(batch_axes(mesh)))
    )
    return state, tokens
