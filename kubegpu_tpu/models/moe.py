"""Mixture-of-Experts transformer with expert parallelism (EP).

The third reference workload: exercises the expert-parallel sharding the
placement layer serves (SURVEY.md §2.2 — the framework hands JAX an
ICI-contiguous sub-mesh so the MoE all-to-all rides ICI, exactly the
workload class the reference gang-scheduled as multi-pod training jobs).

TPU-first routing design (GShard/Switch recipe, NOT a CUDA-style gather):

- **Static capacity, grouped routing.** Each batch row is a routing group:
  every expert processes exactly ``capacity`` token slots per group;
  overflowing tokens are dropped (their residual branch contributes zero).
  Shapes never depend on routing decisions, so the whole layer jits to one
  XLA program with no dynamic shapes — and because slots are assigned
  within a row, nothing serializes across the data-sharded batch dim.
- **Einsum dispatch.** Tokens are routed with one-hot dispatch/combine
  tensors and ``einsum`` — batched matmuls that tile onto the MXU.  With the
  expert dim of the dispatched tensor sharded over the "expert" mesh axis
  (``constrain_expert_grouped``), GSPMD lowers the dispatch einsum to an
  all-to-all over ICI; nothing here opens a transport.
- **Top-1 (Switch) routing** with the Switch load-balancing auxiliary loss,
  exposed via ``sow("intermediates", "aux_loss", ...)`` so the train step
  can weigh it without threading extra return values through flax.
"""

from __future__ import annotations

import math
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubegpu_tpu.models.transformer import CausalSelfAttention
from kubegpu_tpu.parallel.sharding import (
    constrain_expert_grouped,
    constrain_seq_sharded,
)


class MoEMLP(nn.Module):
    """MoE feed-forward layer with static capacity and selectable router.

    ``router_type``:

    - ``"top1"`` — Switch routing: each token to its argmax expert, with
      the Switch load-balancing aux loss.  Overflow past capacity drops.
    - ``"top2"`` — GShard-style: each token to its top TWO experts with
      renormalized gates; second choices claim capacity slots AFTER first
      choices (priority routing), so a token only goes fully unprocessed
      when BOTH its experts overflow — the token-drop rate falls roughly
      quadratically vs top-1 at the same imbalance.
    - ``"expert_choice"`` — experts pick their top-``capacity`` tokens
      (Zhou et al. 2022): capacity overflow is impossible by
      construction and no aux loss is needed (balance is structural);
      the residual risk is tokens NO expert picks, surfaced as the drop
      rate.  Caveat: an expert's top-k spans the whole row, so token
      i's gate depends on later tokens — fine for the encoder-style /
      perf-bench uses it ships for, NOT causally safe for
      autoregressive LM training losses.

    ``fast_dispatch`` runs the dispatch/combine einsums in the module
    dtype (bf16) with fp32 accumulation (``preferred_element_type``)
    instead of full fp32.  One-hot dispatch entries are exact in bf16;
    combine gate weights round to bf16's 8-bit mantissa (~0.4% worst
    case) — measured as the cheap end of the routing-overhead attack
    (VERDICT r4 next #4: the MXU runs bf16 ~4x fp32).

    ``dispatch_impl`` picks how tokens physically move:

    - ``"einsum"`` — dense one-hot dispatch/combine tensors [b, s, e, c]
      contracted over s.  With c = s·cf/e that is O(cf·b·s²·d) MACs —
      QUADRATIC in sequence length; at the bench config (s 512, d 512,
      cf 2) the two einsums alone are ~50% of the expert MLP's FLOPs,
      which is where the measured +51% step overhead vs the dense twin
      lives (BENCH_r04 moe_ms_per_step).
    - ``"gather"`` — index-form: scatter the inverse (expert, slot) →
      token map ([b, e, c], one int per SLOT, no duplicate targets by
      the slot-cumsum construction), gather token rows into expert
      order, and gather-combine each token's k expert outputs back.
      O(b·s·cf·d) data movement, no s² term.  Same arithmetic to fp32
      tolerance, same sharding surface (the [b, e, c, d] tensor still
      crosses the expert axis, so GSPMD still lowers an all-to-all
      under EP).  MEASURED (v5e-1, h2048 L4 e4 cf2, r5): the MXU eats
      the one-hot einsums faster than the VPU runs gather/scatter-add
      until the s² term dominates — einsum wins at s1024 (177 vs
      184 ms) and s2048 (465 vs 473 ms); gather wins at s4096 (508 vs
      539 ms, b4+remat) and is the only option once the O(cf·s²)
      dispatch tensors themselves stop fitting.  Hence the shipped
      default stays ``einsum``; pick ``gather`` for long-sequence MoE.
      ``expert_choice`` always uses the dense path: its combine
      scatter-adds duplicate token targets, which IS the one-hot
      einsum."""

    num_experts: int
    capacity_factor: float = 2.0
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    router_type: str = "top1"
    fast_dispatch: bool = True
    dispatch_impl: str = "einsum"

    def _top1_core(self, gates, capacity):
        """Switch routing decisions in [b, s, e]-sized tensors (shared by
        the dense-einsum and index-form dispatch paths)."""
        b, s, e = gates.shape
        expert_index = jnp.argmax(gates, axis=-1)                   # [b, s]
        mask = jax.nn.one_hot(expert_index, e, dtype=jnp.float32)   # [b, s, e]
        gate = jnp.sum(gates * mask, axis=-1)                       # [b, s]

        # Switch aux loss (their eq. 4): e * Σ_i fraction_routed_i *
        # mean_prob_i, = 1.0 at perfect balance.
        density = jnp.mean(mask, axis=(0, 1))
        density_proxy = jnp.mean(gates, axis=(0, 1))
        aux = e * jnp.sum(density * density_proxy)

        # Position of each token within its expert's per-group capacity
        # (1-based along the row); tokens past capacity are dropped.
        # Integer cumsum: fp32 would silently merge slots past 2^24.
        imask = mask.astype(jnp.int32)
        position = jnp.cumsum(imask, axis=1) * imask                # [b, s, e]
        keep = ((position > 0) & (position <= capacity)).astype(jnp.float32)
        drop = 1.0 - jnp.sum(keep) / (b * s)
        return expert_index, mask, gate, position, keep, aux, drop

    def _route_top1(self, gates, capacity):
        _, mask, gate, position, keep, aux, drop = self._top1_core(
            gates, capacity
        )
        slot = jnp.maximum(position - 1, 0)                         # 0-based
        dispatch = keep[..., None] * jax.nn.one_hot(
            slot, capacity, dtype=jnp.float32
        )                                                           # [b, s, e, c]
        combine = dispatch * gate[..., None, None]
        return dispatch, combine, aux, drop

    def _route_top1_idx(self, gates, capacity):
        """Index form: per token, (expert, slot, gate, keep) with k=1."""
        expert_index, mask, gate, position, keep, aux, drop = self._top1_core(
            gates, capacity
        )
        imask = mask.astype(jnp.int32)
        slot_tok = jnp.sum(jnp.maximum(position - 1, 0) * imask, axis=-1)
        keep_tok = jnp.sum(keep * mask, axis=-1)
        return (
            expert_index[..., None],
            slot_tok[..., None],
            gate[..., None],
            keep_tok[..., None],
            aux,
            drop,
        )

    def _top2_core(self, gates, capacity):
        """GShard top-2 routing decisions in [b, s, e]-sized tensors."""
        b, s, e = gates.shape
        idx1 = jnp.argmax(gates, axis=-1)
        m1 = jax.nn.one_hot(idx1, e, dtype=jnp.float32)
        g1 = jnp.sum(gates * m1, axis=-1)
        idx2 = jnp.argmax(gates * (1.0 - m1), axis=-1)
        m2 = jax.nn.one_hot(idx2, e, dtype=jnp.float32)
        g2 = jnp.sum(gates * m2, axis=-1)
        denom = g1 + g2 + 1e-9                                      # renorm
        g1, g2 = g1 / denom, g2 / denom

        # aux loss judged on FIRST choices (the Switch formula): the
        # balancing pressure targets where the mass is
        density = jnp.mean(m1, axis=(0, 1))
        density_proxy = jnp.mean(gates, axis=(0, 1))
        aux = e * jnp.sum(density * density_proxy)

        # priority slots: first choices claim capacity, second choices
        # fill what remains — disjoint by construction (second positions
        # start past the expert's first-choice count)
        im1, im2 = m1.astype(jnp.int32), m2.astype(jnp.int32)
        pos1 = jnp.cumsum(im1, axis=1) * im1
        used1 = jnp.sum(im1, axis=1)                                # [b, e]
        pos2 = (jnp.cumsum(im2, axis=1) + used1[:, None, :]) * im2
        keep1 = ((pos1 > 0) & (pos1 <= capacity)).astype(jnp.float32)
        keep2 = ((pos2 > 0) & (pos2 <= capacity)).astype(jnp.float32)
        # drop rate = tokens with NO surviving expert (the
        # quality-relevant event; a lost second choice still leaves the
        # token processed)
        covered = jnp.clip(
            jnp.sum(keep1, axis=-1) + jnp.sum(keep2, axis=-1), 0.0, 1.0
        )
        drop = 1.0 - jnp.mean(covered)
        return (idx1, m1, g1, pos1, keep1), (idx2, m2, g2, pos2, keep2), aux, drop

    def _route_top2(self, gates, capacity):
        (c1, c2, aux, drop) = self._top2_core(gates, capacity)
        (_, _, g1, pos1, keep1), (_, _, g2, pos2, keep2) = c1, c2
        d1 = keep1[..., None] * jax.nn.one_hot(
            jnp.maximum(pos1 - 1, 0), capacity, dtype=jnp.float32
        )
        d2 = keep2[..., None] * jax.nn.one_hot(
            jnp.maximum(pos2 - 1, 0), capacity, dtype=jnp.float32
        )
        dispatch = d1 + d2
        combine = d1 * g1[..., None, None] + d2 * g2[..., None, None]
        return dispatch, combine, aux, drop

    def _route_top2_idx(self, gates, capacity):
        """Index form: per token, (expert, slot, gate, keep) with k=2 —
        first- and second-choice slots are disjoint by the priority-slot
        construction, so the scatter has no duplicate targets."""
        (c1, c2, aux, drop) = self._top2_core(gates, capacity)
        outs = []
        for idx, m, g, pos, keep in (c1, c2):
            im = m.astype(jnp.int32)
            slot_tok = jnp.sum(jnp.maximum(pos - 1, 0) * im, axis=-1)
            keep_tok = jnp.sum(keep * m, axis=-1)
            outs.append((idx, slot_tok, g, keep_tok))
        e_idx = jnp.stack([outs[0][0], outs[1][0]], axis=-1)
        slot = jnp.stack([outs[0][1], outs[1][1]], axis=-1)
        gate = jnp.stack([outs[0][2], outs[1][2]], axis=-1)
        keep = jnp.stack([outs[0][3], outs[1][3]], axis=-1)
        return e_idx, slot, gate, keep, aux, drop

    def _route_expert_choice(self, gates, capacity):
        b, s, e = gates.shape
        scores = gates.transpose(0, 2, 1)                           # [b, e, s]
        vals, idx = jax.lax.top_k(scores, capacity)                 # [b, e, c]
        dispatch = jax.nn.one_hot(idx, s, dtype=jnp.float32)        # [b, e, c, s]
        dispatch = dispatch.transpose(0, 3, 1, 2)                   # [b, s, e, c]
        combine = dispatch * vals[:, None, :, :]
        # balance is structural; the aux slot still sows so the train
        # step's weighting is router-agnostic
        aux = jnp.ones(())
        covered = jnp.clip(jnp.sum(dispatch, axis=(2, 3)), 0.0, 1.0)
        drop = 1.0 - jnp.mean(covered)
        return dispatch, combine, aux, drop

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        e = self.num_experts
        h = d * self.mlp_ratio
        # GShard-style GROUPED routing: each batch row is a routing group
        # with its own capacity, so (a) dispatch/combine tensors are
        # [b, s, e, c] — linear in tokens, not O(n^2) — and (b) the slot
        # cumsum runs within a row, never across the data-sharded batch dim,
        # keeping the only cross-device collective the expert all-to-all.
        capacity = min(s, int(math.ceil(s * self.capacity_factor / e)))

        # Router in fp32: softmax/argmax over expert logits must not lose
        # ties to bf16 rounding, and the aux loss needs accurate densities.
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(x.astype(jnp.float32))
        gates = jax.nn.softmax(router_logits, axis=-1)              # [b, s, e]
        if self.router_type not in ("top1", "top2", "expert_choice"):
            raise ValueError(
                f"unknown router_type {self.router_type!r}; expected "
                "top1 | top2 | expert_choice"
            )
        if self.dispatch_impl not in ("einsum", "gather"):
            raise ValueError(
                f"unknown dispatch_impl {self.dispatch_impl!r}; expected "
                "einsum | gather"
            )

        stacked_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=-2, out_axis=-1, batch_axis=(0,)
        )
        w_up = self.param("w_up", stacked_init, (e, d, h), jnp.float32)
        w_down = self.param("w_down", stacked_init, (e, h, d), jnp.float32)

        def run_experts(expert_in):
            """[b, e, c, d] module-dtype in expert order → expert outputs;
            the constrain on both sides shards the expert dim, so under EP
            GSPMD lowers the surrounding movement to an all-to-all."""
            expert_in = constrain_expert_grouped(expert_in)
            mid = nn.gelu(
                jnp.einsum("becd,edh->bech", expert_in, w_up.astype(self.dtype))
            )
            expert_out = jnp.einsum(
                "bech,ehd->becd", mid, w_down.astype(self.dtype)
            )
            return constrain_expert_grouped(expert_out)

        use_gather = self.dispatch_impl == "gather" and self.router_type in (
            "top1",
            "top2",
        )
        if use_gather:
            route_idx = {
                "top1": self._route_top1_idx,
                "top2": self._route_top2_idx,
            }[self.router_type]
            e_idx, slot, gate, keep, aux, drop = route_idx(gates, capacity)
            self.sow("intermediates", "aux_loss", aux)
            self.sow("intermediates", "drop_rate", drop)
            c = capacity
            k = e_idx.shape[-1]
            comp = self.dtype if self.fast_dispatch else jnp.float32
            bidx = jnp.arange(b)[:, None, None]
            tok = jnp.broadcast_to(
                jnp.arange(s, dtype=jnp.int32)[None, :, None], (b, s, k)
            )
            # dropped choices write out of range; mode="drop" discards them
            slot_w = jnp.where(keep > 0, slot, c)
            src = (
                jnp.zeros((b, e, c), jnp.int32)
                .at[bidx, e_idx, slot_w]
                .set(tok, mode="drop")
            )
            filled = (
                jnp.zeros((b, e, c), comp)
                .at[bidx, e_idx, slot_w]
                .set(1.0, mode="drop")
            )
            expert_in = (
                jnp.take_along_axis(
                    x.astype(comp), src.reshape(b, e * c)[:, :, None], axis=1
                ).reshape(b, e, c, d)
                * filled[..., None]
            )
            expert_out = run_experts(expert_in.astype(self.dtype))
            # combine: gather each token's k expert outputs, fp32-weighted sum
            flat = expert_out.astype(comp).reshape(b, e * c, d)
            pick = e_idx * c + jnp.minimum(slot, c - 1)
            picked = jnp.take_along_axis(
                flat, pick.reshape(b, s * k)[:, :, None], axis=1
            ).reshape(b, s, k, d)
            w = (gate * keep).astype(jnp.float32)[..., None]
            out = jnp.sum(picked.astype(jnp.float32) * w, axis=2)
            return out.astype(x.dtype)

        route = {
            "top1": self._route_top1,
            "top2": self._route_top2,
            "expert_choice": self._route_expert_choice,
        }[self.router_type]
        dispatch, combine, aux, drop = route(gates, capacity)
        self.sow("intermediates", "aux_loss", aux)
        # Token-drop rate (VERDICT r3 weak #7): static capacity drops
        # overflow tokens SILENTLY (their residual branch contributes
        # zero), so a misconfigured capacity_factor degrades quality with
        # no signal.  Sown per layer; the train step averages it into a
        # step metric and the worker/bench surface it.
        self.sow("intermediates", "drop_rate", drop)

        # Dispatch → [b, e, c, d]; expert dim sharded (the all-to-all).
        if self.fast_dispatch:
            expert_in = jnp.einsum(
                "bsec,bsd->becd", dispatch.astype(self.dtype),
                x.astype(self.dtype),
                preferred_element_type=jnp.float32,
            )
        else:
            expert_in = jnp.einsum(
                "bsec,bsd->becd", dispatch, x.astype(jnp.float32)
            )
        expert_out = run_experts(expert_in.astype(self.dtype))

        # Combine (the return all-to-all); fp32 accumulation of the weighted sum.
        if self.fast_dispatch:
            out = jnp.einsum(
                "bsec,becd->bsd", combine.astype(self.dtype), expert_out,
                preferred_element_type=jnp.float32,
            )
        else:
            out = jnp.einsum(
                "bsec,becd->bsd", combine, expert_out.astype(jnp.float32)
            )
        return out.astype(x.dtype)


def moe_router_stats(model, params, tokens):
    """(aux_loss, drop_rate) means over layers from one forward's sown
    intermediates — the operator-facing routing health metrics (a
    capacity_factor too low for the token distribution shows up here as a
    rising drop rate, NOT in the loss curve until quality already
    suffered).  Jit-safe; bench.py and the worker report these."""
    _, mutated = model.apply({"params": params}, tokens, mutable=["intermediates"])
    flat, _ = jax.tree_util.tree_flatten_with_path(
        mutated.get("intermediates", {})
    )

    def mean_of(name):
        leaves = [
            leaf
            for path, leaf in flat
            if any(getattr(k, "key", None) == name for k in path)
        ]
        return (
            sum(jnp.mean(x) for x in leaves) / max(len(leaves), 1)
            if leaves
            else jnp.zeros(())
        )

    return mean_of("aux_loss"), mean_of("drop_rate")


class MoeBlock(nn.Module):
    """Pre-LN transformer block whose MLP is a Switch MoE layer."""

    num_heads: int
    num_experts: int
    capacity_factor: float = 2.0
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    sequence_parallel: bool = False
    attn_impl: str = "einsum"
    router_type: str = "top1"
    fast_dispatch: bool = True
    dispatch_impl: str = "einsum"

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + CausalSelfAttention(
            self.num_heads, self.dtype, self.attn_impl, name="attn"
        )(y)
        if self.sequence_parallel:
            x = constrain_seq_sharded(x)
        y = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        x = x + MoEMLP(
            num_experts=self.num_experts,
            capacity_factor=self.capacity_factor,
            mlp_ratio=self.mlp_ratio,
            dtype=self.dtype,
            router_type=self.router_type,
            fast_dispatch=self.fast_dispatch,
            dispatch_impl=self.dispatch_impl,
            name="moe_mlp",
        )(y)
        if self.sequence_parallel:
            x = constrain_seq_sharded(x)
        return x


class MoeTransformerLM(nn.Module):
    """Decoder-only LM where every block's FFN is a Switch MoE layer."""

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    hidden: int = 512
    num_experts: int = 8
    capacity_factor: float = 2.0
    max_seq: int = 2048
    dtype: jnp.dtype = jnp.bfloat16
    sequence_parallel: bool = False
    attn_impl: str = "einsum"
    router_type: str = "top1"
    fast_dispatch: bool = True
    dispatch_impl: str = "einsum"
    # rematerialize blocks in the backward (jax.checkpoint): the same
    # long-context memory knob as TransformerLM.remat; the sown aux_loss
    # intermediates survive nn.remat
    remat: bool = False

    @nn.compact
    def __call__(self, tokens):
        b, s = tokens.shape
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype, name="embed")(
            tokens
        )
        pos = nn.Embed(self.max_seq, self.hidden, dtype=self.dtype, name="pos_embed")(
            jnp.arange(s)[None, :]
        )
        x = x + pos
        block = partial(
            nn.remat(MoeBlock) if self.remat else MoeBlock,
            num_heads=self.num_heads,
            num_experts=self.num_experts,
            capacity_factor=self.capacity_factor,
            dtype=self.dtype,
            sequence_parallel=self.sequence_parallel,
            attn_impl=self.attn_impl,
            router_type=self.router_type,
            fast_dispatch=self.fast_dispatch,
            dispatch_impl=self.dispatch_impl,
        )
        for i in range(self.num_layers):
            x = block(name=f"layer{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(
            self.vocab_size, use_bias=False, dtype=jnp.float32, name="lm_head"
        )(x)
