"""Training-worker entrypoint for the sample pod specs (SURVEY.md §3.4).

This is the process that runs *inside* the scheduled containers — the
workload side of the framework's env contract.  It consumes exactly what the
CRI shim injects (crishim/inject.py):

    TPU_VISIBLE_CHIPS        which host chips this container may claim
    TPU_WORKER_ID            this worker's index in the gang
    JAX_COORDINATOR_ADDRESS  worker 0's host:port for jax.distributed
    JAX_NUM_PROCESSES        gang size
    JAX_PROCESS_ID           == TPU_WORKER_ID

and trains the selected workload under pjit over the gang's chips, printing
the pod-visible half of the north-star metric: time from process start to
the first completed optimizer step (BASELINE.json: schedule-to-first-step
< 60 s).

Every workload family the framework places is launchable here — the full
SURVEY §2.2 stack, not just the DP sample:

    --model resnet50    data-parallel ResNet-50 over ("data",)      [config 4]
    --model lm          transformer LM, Megatron TP + sequence-parallel
                        activations over ("data", "model")          [--tp]
    --model lm-cp       context-parallel LM: ring/ulysses attention
                        over ("data", "seq") for long context       [--cp]
    --model moe         MoE transformer, expert parallelism over
                        ("data", "expert")                          [--ep]
    --model pp          GPipe-pipelined LM over ("pipe",)           [--microbatches]
    --model decode      SERVING: KV-cached greedy decode of the lm
                        family's checkpoints (models/decoding.py)   [--prompt-len]

Single-worker mode (JAX_NUM_PROCESSES absent or 1) skips the distributed
rendezvous, so the same image serves BASELINE configs 2-5.

    python -m kubegpu_tpu.models.worker --steps 20 --batch-per-chip 32
    python -m kubegpu_tpu.models.worker --model lm --tp 4 --seq 1024
    python -m kubegpu_tpu.models.worker --model lm-cp --cp 4 --seq 8192
"""

from __future__ import annotations

import argparse
import logging
import os
import time

log = logging.getLogger("kubegpu_tpu.worker")

RESNET_MODELS = ("resnet50", "resnet50-unrolled", "resnet-tiny")
LM_MODELS = ("lm", "lm-cp", "moe")


def initialize_distributed() -> None:
    """jax.distributed rendezvous from the injected env; no-op when solo."""
    import jax

    num = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
    if num <= 1:
        return
    coordinator = os.environ["JAX_COORDINATOR_ADDRESS"]
    pid = int(os.environ.get("JAX_PROCESS_ID", os.environ.get("TPU_WORKER_ID", "0")))
    log.info("jax.distributed.initialize(%s, num=%d, id=%d)", coordinator, num, pid)
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=num, process_id=pid
    )


def _worker_id() -> int:
    return int(
        os.environ.get("JAX_PROCESS_ID", os.environ.get("TPU_WORKER_ID", "0")) or 0
    )


def _split_mesh(n: int, parallel: int, parallel_axis: str):
    """(data, parallel) axis sizes for n devices with `parallel`-way model/
    expert/seq parallelism; parallel=0 means 'all devices'."""
    p = parallel or n
    if n % p != 0:
        raise SystemExit(
            f"--{parallel_axis} {p} does not divide the device count {n}"
        )
    return n // p, p


class _CheckpointHooks:
    """Optional Orbax checkpoint/resume for TrainState-shaped workloads,
    namespaced per model variant so different param layouts never collide."""

    def __init__(self, args, state):
        import jax

        from kubegpu_tpu.models.checkpoint import (
            make_manager,
            restore_checkpoint,
            save_checkpoint,
        )

        self._save = save_checkpoint
        root = os.path.abspath(args.ckpt_dir)
        try:
            legacy = sorted(
                d for d in os.listdir(root)
                if d.isdigit() and os.path.isdir(os.path.join(root, d))
            )
        except OSError:
            legacy = []
        if legacy:
            # checkpoints written before per-model namespacing live at the
            # root; their param layout may not match this model variant, so
            # they are NOT restored — but silence here would look like a
            # silent restart from step 0
            log.warning(
                "ignoring legacy checkpoints at %s (steps %s); checkpoints "
                "now live under %s — restore manually if the layouts match",
                root, ",".join(legacy), os.path.join(root, args.model),
            )
        self.mgr = make_manager(os.path.join(root, args.model))
        self.start_step = 0
        self.last_saved = -1
        restored = restore_checkpoint(self.mgr, state)
        self.state = state
        if restored is not None:
            self.state = restored
            self.start_step = int(jax.device_get(restored.step))
            print(f"RESUMED step={self.start_step}", flush=True)

    def maybe_save(self, state, done: int, every: int) -> float:
        if every <= 0 or done % every != 0:
            return 0.0
        ts = time.monotonic()
        self.last_saved = self._save(self.mgr, state)
        return time.monotonic() - ts

    def finish(self, state) -> None:
        import jax

        final_step = int(jax.device_get(state.step))
        if final_step != self.last_saved:  # orbax raises on duplicate saves
            self._save(self.mgr, state)
        self.mgr.wait_until_finished()
        print(f"CHECKPOINT_SAVED step={final_step}", flush=True)


def _measured_loop(args, t0, run_once, state, batches, per_step_items, unit,
                   ckpt=None) -> int:
    """The measurement protocol, shared by every runner: first step timed
    from process start (FIRST_STEP_DONE — the pod-visible half of the north
    star), then a steps-1 steady loop with optional checkpoint saves and a
    throughput line.  ``run_once(state, batch_or_None) -> (state, loss)``;
    ``batches=None`` means the resident (constant-batch) mode.  Syncs are
    scalar VALUE readbacks — float(loss) — because block_until_ready can
    return early on tunnelled backends."""
    start_step = 0
    if ckpt is not None:
        state, start_step = ckpt.state, ckpt.start_step

    state, loss = run_once(state, next(batches) if batches is not None else None)
    loss_v = float(loss)  # forces the step to completion
    first_step_s = time.monotonic() - t0
    print(
        f"FIRST_STEP_DONE seconds={first_step_s:.2f} loss={loss_v:.4f}",
        flush=True,
    )

    t1 = time.monotonic()
    save_s = 0.0
    done = start_step + 1
    for _ in range(args.steps - 1):
        state, loss = run_once(
            state, next(batches) if batches is not None else None
        )
        done += 1
        if ckpt is not None:
            save_s += ckpt.maybe_save(state, done, args.ckpt_every)
    loss_v = float(loss)  # forces the whole chain
    dt = time.monotonic() - t1 - save_s
    if args.steps > 1:
        rate = per_step_items * (args.steps - 1) / dt
        print(f"steady_state {unit}={rate:.1f} loss={loss_v:.4f}", flush=True)
    if ckpt is not None:
        ckpt.finish(state)
    return 0


def _make_batches(args, source, sharding, resident_batch):
    """--data dispatch shared by the runners: synthetic = device-resident
    pool, stream = double-buffered prefetch, resident = one constant batch
    (returns batches=None).  ``resident_batch()`` builds the constant."""
    from kubegpu_tpu.models.data import device_pool_batches, prefetch_to_device

    if args.data == "synthetic":
        batches = device_pool_batches(source, sharding, pool=max(args.data_pool, 1))
        return batches, next(batches)
    if args.data == "stream":
        batches = prefetch_to_device(source, sharding, depth=2)
        return batches, next(batches)
    return None, resident_batch()


def _run_resnet(args, t0: float) -> int:
    import jax
    import jax.numpy as jnp

    from kubegpu_tpu.models import (
        ResNet,
        ResNet50,
        ScanResNet50,
        create_train_state,
        make_resnet_train_step,
        place_resnet,
    )
    from kubegpu_tpu.models.data import synthetic_image_batches
    from kubegpu_tpu.parallel import device_mesh
    from kubegpu_tpu.parallel.sharding import batch_sharding

    n = jax.device_count()
    mesh = device_mesh({"data": n})
    classes = args.num_classes
    if args.model == "resnet50":
        model = ScanResNet50(num_classes=classes)
        size = args.image_size
    elif args.model == "resnet50-unrolled":
        model = ResNet50(num_classes=classes)
        size = args.image_size
    else:  # CI-sized twin, same code path
        # the data source must draw labels from the SAME label space as the
        # model head — out-of-range labels make take_along_axis poison the
        # loss with garbage/NaN
        classes = 10
        model = ResNet(stage_sizes=(1, 1, 1, 1), num_filters=8, num_classes=classes)
        size = 32

    batch = args.batch_per_chip * n
    rng = jax.random.PRNGKey(0)
    # input pipeline: each process generates ONLY its local rows of the
    # global batch (put_global assembles the global array), seeded by the
    # same id chain the rendezvous uses so gang workers draw disjoint
    # streams however the env named them (pure DP: every chip is its own
    # data shard, so per-process streams never need to agree)
    local_batch = args.batch_per_chip * jax.local_device_count()
    source = synthetic_image_batches(
        local_batch, size=size, num_classes=classes, worker_id=_worker_id(),
    )
    batches, (images, labels) = _make_batches(
        args, source, batch_sharding(mesh),
        lambda: (
            jnp.ones((batch, size, size, 3), jnp.float32),
            jnp.zeros((batch,), jnp.int32),
        ),
    )
    state = create_train_state(model, rng, images)
    state, images, labels = place_resnet(state, (images, labels), mesh)
    step = make_resnet_train_step(mesh)
    const = (images, labels)

    def run_once(state, b):
        im, lb = b if b is not None else const
        return step(state, im, lb)

    ckpt = _CheckpointHooks(args, state) if args.ckpt_dir else None
    return _measured_loop(
        args, t0, run_once, state, batches, batch, "images_per_sec", ckpt
    )


def _run_lm_family(args, t0: float) -> int:
    """lm (TP+SP), lm-cp (context parallel), moe (expert parallel)."""
    import jax

    from kubegpu_tpu.models import (
        MoeTransformerLM,
        TransformerLM,
        create_train_state,
        make_lm_train_step,
        make_moe_train_step,
        place_cp_lm,
        place_lm,
        place_moe,
    )
    from kubegpu_tpu.models.data import (
        put_global,
        synthetic_token_batches_for_mesh,
    )
    from kubegpu_tpu.parallel import device_mesh
    from kubegpu_tpu.parallel.sharding import batch_sharding

    n = jax.device_count()
    if args.model == "lm":
        dp, tp = _split_mesh(n, args.tp, "tp")
        mesh = device_mesh({"data": dp, "model": tp})
        if args.heads % tp:
            raise SystemExit(f"--heads {args.heads} not divisible by tp={tp}")
        model = TransformerLM(
            vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
            hidden=args.hidden, max_seq=args.seq + 1,
            sequence_parallel=True, attn_impl=args.attn_impl,
            remat=args.remat,
        )
        place, make_step = place_lm, make_lm_train_step
    elif args.model == "lm-cp":
        dp, cp = _split_mesh(n, args.cp, "cp")
        mesh = device_mesh({"data": dp, "seq": cp})
        if args.seq % cp:
            raise SystemExit(f"--seq {args.seq} not divisible by cp={cp}")
        model = TransformerLM(
            vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
            hidden=args.hidden, max_seq=args.seq + 1,
            context_parallel=True,
            attn_impl=args.attn_impl if args.attn_impl != "flash" else "ring",
            remat=args.remat,
        )
        place, make_step = place_cp_lm, make_lm_train_step
    else:  # moe — EP, optionally x TP (--tp shards each expert's FFN too)
        tp = max(args.tp, 1)
        if n % tp:
            raise SystemExit(f"--tp {tp} does not divide the device count {n}")
        dp, ep = _split_mesh(n // tp, args.ep, "ep")
        axes = {"data": dp, "expert": ep}
        if tp > 1:
            if args.heads % tp:
                raise SystemExit(f"--heads {args.heads} not divisible by tp={tp}")
            axes["model"] = tp
        mesh = device_mesh(axes)
        model = MoeTransformerLM(
            vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
            hidden=args.hidden, num_experts=args.num_experts or ep,
            capacity_factor=2.0, max_seq=args.seq + 1, remat=args.remat,
            router_type=args.moe_router, dispatch_impl=args.moe_dispatch,
        )
        place, make_step = place_moe, make_moe_train_step

    batch = max(args.batch_per_chip * mesh.shape.get("data", 1), 1)
    # per-DATA-SHARD seeding: processes replicating one shard (tp/cp axes)
    # draw byte-identical rows, distinct shards draw disjoint streams —
    # anything else silently stitches divergent "replicas" into the global
    # array and the tp/cp collectives mix activations from different inputs
    source = synthetic_token_batches_for_mesh(
        batch, args.seq + 1, args.vocab, mesh
    )
    batches, tokens = _make_batches(
        args, source, batch_sharding(mesh),
        lambda: put_global(next(source), batch_sharding(mesh)),
    )
    state = create_train_state(model, jax.random.PRNGKey(0), tokens[:, :-1])
    state, tokens = place(state, tokens, mesh)
    step = make_step(mesh)

    def run_once(state, b):
        out = step(state, tokens if b is None else b)
        return out[0], out[1]  # moe returns (state, loss, aux)

    ckpt = _CheckpointHooks(args, state) if args.ckpt_dir else None
    return _measured_loop(
        args, t0, run_once, state, batches, batch * args.seq,
        "tokens_per_sec", ckpt,
    )


def _run_pp(args, t0: float) -> int:
    """GPipe-pipelined LM over a ("pipe",) mesh; params/opt are a raw
    pytree (no TrainState), so checkpointing is declined explicitly."""
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubegpu_tpu.models import (
        init_pipeline_lm,
        make_pipeline_lm_train_step,
        place_pipeline_lm,
    )
    from kubegpu_tpu.models.data import put_global, synthetic_token_batches
    from kubegpu_tpu.parallel import device_mesh

    if args.ckpt_dir:
        log.warning("--ckpt-dir is not supported for --model pp; ignoring")
    n = jax.device_count()
    stages = args.pp_stages or n
    if n % stages:
        raise SystemExit(f"--pp-stages {stages} does not divide {n} devices")
    if jax.process_count() > 1 and stages != n:
        # a sub-mesh would leave some gang processes with no addressable
        # mesh devices, wedging their put_global and the collective steps
        raise SystemExit(
            f"--pp-stages {stages} != device count {n}: in a multi-process "
            "gang the pipeline must span every device"
        )
    rounds = max(args.pp_rounds, 1)
    if rounds > 1 and args.microbatches < stages:
        raise SystemExit(
            f"--pp-rounds {rounds} (circular schedule) needs "
            f"--microbatches >= stages ({args.microbatches} < {stages})"
        )
    mesh = device_mesh({"pipe": stages}, devices=jax.devices()[:stages])
    batch = max(args.batch_per_chip, 1) * max(args.microbatches, 1)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=args.vocab,
        num_stages=stages * rounds, layers_per_stage=args.layers,
        hidden=args.hidden, max_seq=args.seq + 1,
    )
    if rounds > 1:
        from kubegpu_tpu.models import to_circular_layout

        params = to_circular_layout(params, stages)
    tx = optax.sgd(0.1, momentum=0.9)
    opt = tx.init(params)
    sharding = NamedSharding(mesh, P())
    # tokens are REPLICATED over the pipe mesh: every process must draw the
    # byte-identical stream, so the worker id must NOT enter the seed
    source = synthetic_token_batches(batch, args.seq + 1, args.vocab)
    batches, tokens = _make_batches(
        args, source, sharding, lambda: put_global(next(source), sharding)
    )
    params, opt, tokens = place_pipeline_lm(
        params, opt, tokens, mesh, num_rounds=rounds
    )
    step = make_pipeline_lm_train_step(
        mesh, tx, num_heads=args.heads, num_microbatches=args.microbatches,
        num_rounds=rounds,
    )
    const = tokens

    def run_once(state, b):
        params, opt = state
        params, opt, loss = step(params, opt, const if b is None else b)
        return (params, opt), loss

    return _measured_loop(
        args, t0, run_once, (params, opt), batches, batch * args.seq,
        "tokens_per_sec", None,
    )


def _draft_for(args, max_seq: int):
    """Draft model params + dims for speculative serving: restored from
    ``--draft-ckpt-dir`` when given (models.serving.load_draft_checkpoint
    — the shared restore/bf16-cast path), fresh-init otherwise (lossless
    for ANY draft; a trained draft is what buys the accept rate).

    Also enforces the ONE speculation headroom rule for both serving
    modes (dense speculative and paged --speculate): a verify window
    writes rows [pos, pos+k], so the cache needs k rows past
    prompt+budget."""
    import jax
    import jax.numpy as jnp

    from kubegpu_tpu.models import TransformerLM
    from kubegpu_tpu.models.decoding import bf16_cast

    if args.prompt_len + args.steps + args.spec_k > max_seq:
        raise SystemExit(
            f"--prompt-len {args.prompt_len} + --steps {args.steps} + "
            f"--spec-k {args.spec_k} exceeds --seq+1 = {max_seq}: the "
            "speculative verify window needs k rows of cache headroom"
        )

    d_hidden = args.draft_hidden or max(args.hidden // 4, 128)
    d_heads = max(d_hidden // 128, 1)
    if d_hidden % d_heads:
        # fail crisply like the other CLI geometry checks, not with a
        # reshape traceback from inside jax tracing
        raise SystemExit(
            f"--draft-hidden {d_hidden} not divisible by its derived "
            f"head count {d_heads} (heads are d_hidden//128; pick a "
            "multiple of 128)"
        )
    dparams = None
    if args.draft_ckpt_dir:
        from kubegpu_tpu.models.serving import load_draft_checkpoint

        dparams = load_draft_checkpoint(
            args.draft_ckpt_dir, vocab_size=args.vocab,
            num_layers=args.draft_layers, num_heads=d_heads,
            hidden=d_hidden, max_seq=max_seq,
        )
        if dparams is not None:
            print("RESTORED_DRAFT_FOR_SERVING", flush=True)
        else:
            log.warning(
                "no draft checkpoint under %s; speculating with a fresh "
                "draft init (lossless, but accept rate will be ~0)",
                args.draft_ckpt_dir,
            )
    if dparams is None:
        draft = TransformerLM(
            vocab_size=args.vocab, num_layers=args.draft_layers,
            num_heads=d_heads, hidden=d_hidden, max_seq=max_seq,
        )
        dparams = jax.jit(
            lambda r, x: bf16_cast(draft.init(r, x)["params"])
        )(jax.random.PRNGKey(7), jnp.ones((1, 8), jnp.int32))
    return dparams, d_heads, d_hidden


def _run_decode_batched(args, params, max_seq: int, t0: float) -> int:
    """--serving continuous|paged: serve a mixed-length queue through the
    slot-based batchers.  One "request wave" = slots x 2 prompts with
    budgets cycling 1/4..1x --steps; --serve replays waves forever (the
    replica loop), else one wave is timed and reported."""
    import numpy as np

    common = dict(
        vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
        hidden=args.hidden, max_seq=max_seq,
        slots=args.batch_per_chip, prompt_pad=args.prompt_len,
    )
    if args.serve_fp32:
        import jax.numpy as jnp

        common["dtype"] = jnp.float32
    if args.tp > 1 and args.serving != "paged":
        raise SystemExit(
            f"--tp {args.tp} with --serving {args.serving}: tensor-"
            "parallel serving is the PAGED batcher's mesh (--serving "
            "paged); dense/speculative batchers are single-device"
        )
    if args.kv_dtype is not None and args.serving != "paged":
        raise SystemExit(
            f"--kv-dtype {args.kv_dtype} with --serving {args.serving}: "
            "the KV storage format is the PAGED pool's knob "
            "(--serving paged)"
        )
    if args.serving == "continuous":
        from kubegpu_tpu.models.serving import ContinuousBatcher

        cb = ContinuousBatcher(params, **common, quant=args.int8)
    elif args.serving == "speculative":
        from kubegpu_tpu.models.spec_serving import SpeculativeContinuousBatcher

        # the draft: a shrunk twin (or --draft-ckpt-dir weights) — output
        # is token-identical to the dense batcher for ANY draft (greedy
        # verification); a TRAINED draft is what turns the correctness
        # into a speedup (see bench.py trained_quality).  _draft_for
        # enforces the k-row cache-headroom bound
        dparams, d_heads, d_hidden = _draft_for(args, max_seq)
        cb = SpeculativeContinuousBatcher(
            params, dparams, **common, quant=args.int8, k=args.spec_k,
            draft_num_layers=args.draft_layers, draft_num_heads=d_heads,
            draft_hidden=d_hidden,
            # rejection-sampled lossless speculation only when asked:
            # the sampling step program carries the rejection kernel,
            # and the greedy-only program must stay untouched otherwise
            sampling=args.sample_temperature > 0,
            top_k=args.sample_top_k,
        )
    else:
        from kubegpu_tpu.models.paging import PagedContinuousBatcher

        # page must divide prompt_pad (whole-page admit scatter): 128 when
        # it divides, else one page spans the whole prompt pad.
        # --page-size overrides: multi-turn decode sealing needs pages
        # SMALLER than the prompt pad (a chain seals only FULL pages,
        # and a follow turn's prompt must still fit prompt_pad)
        if args.page_size is not None:
            if args.page_size < 1 or args.prompt_len % args.page_size:
                raise SystemExit(
                    f"--page-size {args.page_size} must be positive and "
                    f"divide --prompt-len {args.prompt_len} (whole-page "
                    "admit scatter)"
                )
            page = args.page_size
        else:
            page = 128 if args.prompt_len % 128 == 0 else args.prompt_len
        slots = args.batch_per_chip
        mesh = None
        if args.tp > 1:
            # tensor-parallel paged serving: shard the KV page pool, the
            # station and the draft ring over an ICI "model" mesh — tp x
            # the pool rows (and sessions) per replica for the same
            # per-device HBM, TP-scaled per-token FLOPs.  The replica
            # ADVERTISES its mesh via the SERVING_TP line (and its
            # ledger's tp column at /debug/trace upstream).
            import jax

            from kubegpu_tpu.parallel import device_mesh

            n = jax.device_count()
            if args.tp > n:
                raise SystemExit(
                    f"--tp {args.tp} exceeds the visible device count {n}"
                )
            if args.heads % args.tp:
                raise SystemExit(
                    f"--heads {args.heads} not divisible by tp={args.tp}"
                )
            if args.vocab % args.tp:
                raise SystemExit(
                    f"--vocab {args.vocab} not divisible by tp={args.tp} "
                    "(lm_head is column-parallel over the vocab)"
                )
            mesh = device_mesh(
                {"model": args.tp}, devices=jax.devices()[: args.tp]
            )
            print(
                f"SERVING_TP tp={args.tp} devices="
                + ",".join(str(d.id) for d in mesh.devices.flat),
                flush=True,
            )
        spec_kw = {}
        k_extra = 0
        if args.speculate:
            # _draft_for enforces the k-row cache-headroom bound
            dparams, d_heads, d_hidden = _draft_for(args, max_seq)
            if args.tp > 1 and d_heads % args.tp:
                # crisp like the other CLI geometry checks — the draft
                # ring shards whole heads too, and the DERIVED head
                # count (draft_hidden // 128) is what must divide
                raise SystemExit(
                    f"draft head count {d_heads} (derived from "
                    f"--draft-hidden {d_hidden} // 128) not divisible "
                    f"by tp={args.tp}: pick --draft-hidden = a multiple "
                    f"of {128 * args.tp}"
                )
            spec_kw = dict(
                draft_params=dparams, speculate_k=args.spec_k,
                draft_num_layers=args.draft_layers,
                draft_num_heads=d_heads, draft_hidden=d_hidden,
            )
            k_extra = args.spec_k  # per-sequence page-reservation headroom
        pool = slots * -(
            -(args.prompt_len + args.steps + k_extra) // page
        ) + 1
        if args.kv_dtype is not None:
            # die crisply on a contradictory knob pair (e.g. --kv-dtype
            # bf16 with --serve-fp32) like the other CLI geometry checks
            from kubegpu_tpu.models.serving import resolve_kv_dtype

            import jax.numpy as jnp

            try:
                resolve_kv_dtype(
                    args.kv_dtype, common.get("dtype", jnp.bfloat16)
                )
            except ValueError as e:
                raise SystemExit(str(e))
        cb = PagedContinuousBatcher(
            params, **common, quant=args.int8, page_size=page,
            pool_pages=pool, decode_page_cache=args.decode_page_cache,
            kv_dtype=args.kv_dtype, mesh=mesh,
            # sampled traffic keeps speculation on paged replicas too:
            # the verify runs the rejection sampler in-program (the
            # dense batcher's sampling=True mode) — no silent
            # sampled->unspeculated demotion
            sampling=args.sample_temperature > 0,
            top_k=args.sample_top_k,
            **spec_kw,
        )

    if args.serve_http is not None:
        return _serve_http(args, cb, t0)

    rng = np.random.RandomState(0)
    n_req = args.batch_per_chip * 2
    budgets = [
        max(args.steps * (1 + i % 4) // 4, 1) for i in range(n_req)
    ]
    run_kw = {}
    if args.sample_temperature > 0:
        # sampled decode, seed-pinned: every request i derives its keys
        # from (sample_seed + i, absolute position) — reruns and other
        # replicas with the same knobs produce identical streams
        run_kw = dict(
            temperatures=[args.sample_temperature] * n_req,
            seeds=[args.sample_seed + i for i in range(n_req)],
        )

    def wave():
        prompts = [
            rng.randint(
                0, args.vocab, size=rng.randint(1, args.prompt_len + 1),
                dtype=np.int32,
            )
            for _ in range(n_req)
        ]
        tw = time.monotonic()
        out = cb.run(prompts, budgets, **run_kw)
        dt = time.monotonic() - tw
        total = sum(len(v) for v in out.values())
        return total, dt

    wave()  # warmup: the first wave pays the step/admit compiles
    print(
        f"FIRST_DECODE_DONE seconds={time.monotonic() - t0:.2f}", flush=True
    )
    # the timed wave runs warm, like the static path's post-warmup timing
    total, dt = wave()
    print(
        f"DECODE_DONE tokens_per_sec={total / dt:.1f} serving={args.serving} "
        f"requests={n_req} steps={cb.stats['steps']} "
        f"admits={cb.stats['admits']}",
        flush=True,
    )
    if args.serve:
        while True:
            total, dt = wave()
            print(
                f"SERVING tokens_per_sec={total / dt:.1f}", flush=True
            )
    return 0


def _serve_http(args, cb, t0: float) -> int:
    """--serve-http: expose the batcher as a REPLICA HTTP serving
    endpoint (gateway/dataplane.py) — POST /v1/submit streams one SSE
    event per committed token batch, POST /v1/cancel frees the
    sequence's pages wire-level, GET /v1/state advertises the serving
    contract (tp, page economy), GET /healthz answers the gateway
    registry's probe.  This is the pod-side half of the distributed
    data plane: the gateway dispatches to podIP:port and stitches the
    replica's trace spans under its own dispatch span."""
    import signal
    import threading

    import numpy as np

    from kubegpu_tpu.gateway.dataplane import ReplicaServer

    if bool(args.serve_http_tls_cert) != bool(args.serve_http_tls_key):
        raise SystemExit(
            "--serve-http-tls-cert and --serve-http-tls-key must be "
            "given together"
        )
    # pay the program compiles BEFORE advertising the port: the first
    # real request must meet a warm batcher, not a compile wall
    cb.submit(0, np.asarray([1, 2, 3], np.int32), 2)
    while cb.has_work():
        cb.serve_step()
    auth_token = None
    if args.serve_http_auth_token_file:
        with open(args.serve_http_auth_token_file) as f:
            auth_token = f.read().strip()
    server = ReplicaServer(
        cb, listen=("0.0.0.0", args.serve_http),
        step_delay_s=args.serve_http_step_delay,
        fail_migration=args.serve_http_fail_migration,
        tls_cert=args.serve_http_tls_cert,
        tls_key=args.serve_http_tls_key,
        auth_token=auth_token,
        role=args.role,
    )
    server.start()
    print(
        f"REPLICA_HTTP_SERVING port={server.port} serving={args.serving} "
        f"role={args.role} "
        f"tls={int(server.tls)} seconds={time.monotonic() - t0:.2f}",
        flush=True,
    )
    shutdown = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: shutdown.set())
    try:
        shutdown.wait()
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


def _run_decode(args, t0: float) -> int:
    """Serving mode: KV-cached greedy decode (models/decoding.py) of the
    lm family's param contract.  With --ckpt-dir it restores the TRAINED
    lm checkpoint (written by `--model lm` under <dir>/lm; the decode
    model shares its parameter names, so training output serves directly)
    and serves it in bf16; without, it serves fresh init weights (a pure
    throughput probe).  Single-chip by design: serving replicas scale out
    as pods, the way the scheduler places them."""
    import jax
    import jax.numpy as jnp

    from kubegpu_tpu.models import TransformerLM, create_train_state
    from kubegpu_tpu.models.decoding import greedy_generate
    from kubegpu_tpu.models.train import train_state_template

    max_seq = args.seq + 1  # the lm family trains seq+1 windows; pos_embed
    # (and therefore any restored checkpoint) is sized to it
    if args.serve_http is not None and args.serving not in (
        "continuous", "paged"
    ):
        raise SystemExit(
            f"--serve-http with --serving {args.serving}: the replica "
            "HTTP endpoint drives the incremental serving API "
            "(submit/serve_step/cancel) — use --serving continuous or "
            "--serving paged"
        )
    if args.serving == "static" and args.tp > 1:
        raise SystemExit(
            f"--tp {args.tp} with --serving static: tensor-parallel "
            "serving is the paged batcher's mesh (--serving paged)"
        )
    if args.prompt_len + args.steps > max_seq:
        raise SystemExit(
            f"--prompt-len {args.prompt_len} + --steps {args.steps} exceeds "
            f"the cache size --seq+1 = {max_seq}"
        )
    model = TransformerLM(
        vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
        hidden=args.hidden, max_seq=max_seq,
    )
    rng = jax.random.PRNGKey(0)
    sample = jnp.ones((1, 8), jnp.int32)
    params32 = None
    if args.ckpt_dir:
        from kubegpu_tpu.models.checkpoint import make_manager, restore_checkpoint

        mgr = make_manager(os.path.join(os.path.abspath(args.ckpt_dir), "lm"))
        # restore into an ABSTRACT template (eval_shape): serving must not
        # pay a fresh init, and the checkpoint's params land directly.
        # (The full state tree is restored — orbax's StandardRestore needs
        # structural match, so the optimizer moments cost restore I/O they
        # never serve; an export-for-serving step could halve that later.)
        restored = restore_checkpoint(mgr, train_state_template(model, rng, sample))
        if restored is not None:
            params32 = restored.params
            print(
                f"RESTORED_FOR_SERVING step={int(jax.device_get(restored.step))}",
                flush=True,
            )
            del restored  # drop step/moments promptly
        else:
            log.warning("no lm checkpoint under %s; serving fresh weights",
                        args.ckpt_dir)
    if params32 is None:
        params32 = create_train_state(model, rng, sample).params
    from kubegpu_tpu.models.decoding import bf16_cast

    if args.serve_fp32:
        # fp32 serving: exact greedy determinism across processes and
        # byte-identical sealed decode pages — the precision class the
        # decode-page-cache "fp32" policy shares at, and what the
        # multi-process dryruns gate token identity on
        params = params32
    else:
        params = bf16_cast(params32)
    del params32
    if args.int8:
        # weight-only int8 serving: half the HBM bytes per decode step
        # (decode streams the full parameter set every step); quality
        # deltas are measured in bench.py — greedy argmax tracks bf16
        from kubegpu_tpu.models.decoding import quantize_params_int8

        params = jax.jit(quantize_params_int8)(params)
        print("SERVING_INT8 weight-only per-output-channel", flush=True)

    batch = args.batch_per_chip
    if args.serving != "static":
        return _run_decode_batched(args, params, max_seq, t0)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, args.prompt_len), 0, args.vocab, jnp.int32
    )
    fn = jax.jit(
        lambda p, t: greedy_generate(
            p, t, args.steps, vocab_size=args.vocab, num_layers=args.layers,
            num_heads=args.heads, hidden=args.hidden, max_seq=max_seq,
            quant=args.int8,
        )
    )
    out = fn(params, prompt)
    int(out[0, -1])  # value readback forces the program
    print(f"FIRST_DECODE_DONE seconds={time.monotonic() - t0:.2f}", flush=True)
    n = 3
    ts = time.monotonic()
    for _ in range(n):
        out = fn(params, prompt)
    int(out[0, -1])
    dt = (time.monotonic() - ts) / n
    print(
        f"DECODE_DONE tokens_per_sec={batch * args.steps / dt:.1f} "
        f"ms_per_call={dt * 1e3:.1f}",
        flush=True,
    )
    if args.serve:
        # replica mode (samples/jax-decode.yaml): keep serving until the
        # pod is deleted; one throughput line per report interval
        calls = 0
        ts = time.monotonic()
        while True:
            out = fn(params, prompt)
            int(out[0, -1])
            calls += 1
            if calls % 50 == 0:
                now = time.monotonic()
                print(
                    f"SERVING tokens_per_sec="
                    f"{50 * batch * args.steps / (now - ts):.1f}",
                    flush=True,
                )
                ts = now
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--model",
        default="resnet50",
        choices=list(RESNET_MODELS) + list(LM_MODELS) + ["pp", "decode"],
        help="resnet50 = scan-rolled flagship (fast compile); "
        "resnet50-unrolled = plain per-block variant; lm = TP+SP "
        "transformer; lm-cp = context-parallel LM (ring/ulysses); "
        "moe = expert-parallel MoE; pp = GPipe-pipelined LM; "
        "decode = KV-cached greedy serving of lm checkpoints",
    )
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-per-chip", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    # LM-family shape knobs (worker-pod sized defaults; heads must divide
    # by --tp, --seq by --cp)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--layers", type=int, default=4,
                    help="LM layers (pp: layers PER STAGE)")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--seq", type=int, default=1024,
                    help="tokens per sample (the LM trains on seq+1 windows)")
    ap.add_argument("--attn-impl", default="flash",
                    choices=["einsum", "flash", "ring", "ulysses"],
                    help="lm-cp: ring (default) or ulysses")
    ap.add_argument("--remat", action="store_true",
                    help="lm/lm-cp/moe: rematerialize blocks in the backward "
                    "(activation memory O(seq) instead of O(layers x seq) "
                    "for one extra forward of FLOPs — the long-context "
                    "memory knob, composes with CP)")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel size — lm: 0 = all devices; "
                    "moe: 0 = no TP (EP only), N > 1 Megatron-shards each "
                    "expert's FFN over N devices; decode --serving paged: "
                    "N > 1 shards the KV page pool / station / draft ring "
                    "on heads over an N-device 'model' mesh (N x pool "
                    "rows per replica for the same per-device HBM)")
    ap.add_argument("--cp", type=int, default=0,
                    help="lm-cp: context-parallel size (0 = all devices)")
    ap.add_argument("--ep", type=int, default=0,
                    help="moe: expert-parallel size (0 = all devices)")
    ap.add_argument("--num-experts", type=int, default=0,
                    help="moe: expert count (0 = one per ep shard)")
    ap.add_argument("--moe-router", default="top1",
                    choices=["top1", "top2", "expert_choice"],
                    help="moe: routing algorithm (top2 drops far fewer "
                    "tokens under imbalance; expert_choice is dropless by "
                    "construction but not causal — see models/moe.py)")
    ap.add_argument("--moe-dispatch", default="einsum",
                    choices=["einsum", "gather"],
                    help="moe: token movement — dense one-hot einsums, or "
                    "index-form scatter/gather (no O(seq^2) MACs; "
                    "expert_choice always uses einsum)")
    ap.add_argument("--pp-stages", type=int, default=0,
                    help="pp: pipeline stages (0 = all devices)")
    ap.add_argument("--pp-rounds", type=int, default=1,
                    help="pp: rounds of the circular/interleaved schedule "
                    "(1 = GPipe; V>1 holds V stage slices per device and "
                    "divides the pipeline bubble by ~V)")
    ap.add_argument("--microbatches", type=int, default=4,
                    help="pp: microbatches per step (circular needs >= stages)")
    ap.add_argument("--serve", action="store_true",
                    help="decode: loop forever as a serving replica "
                    "(default: benchmark a few calls and exit)")
    ap.add_argument("--serve-http", type=int, default=None, metavar="PORT",
                    help="decode --serving continuous|paged: serve as a "
                    "REPLICA HTTP endpoint on this port (0 = ephemeral; "
                    "the chosen port prints as REPLICA_HTTP_SERVING) — "
                    "POST /v1/submit streams committed token batches as "
                    "SSE, /v1/cancel frees pages wire-level, /healthz "
                    "answers the gateway's probe.  The gateway "
                    "(gateway/server.py --replica-port) dispatches here")
    ap.add_argument("--role", choices=("prefill", "decode", "flex"),
                    default="flex",
                    help="--serve-http: this replica's serving role in a "
                    "disaggregated fleet.  'prefill' parks sequences the "
                    "moment their prompt pages seal (the gateway hands "
                    "them off over /v1/export -> /v1/import); 'decode' "
                    "advertises itself as a handoff target; 'flex' (the "
                    "default) serves both phases co-located.  Mutable at "
                    "runtime via POST /v1/role")
    ap.add_argument("--serve-http-step-delay", type=float, default=0.0,
                    metavar="S",
                    help="--serve-http: sleep this long between serving "
                    "iterations (0 = flat out).  Chaos/test knob: slows "
                    "the loop so kill/cancel schedules land provably "
                    "mid-stream")
    ap.add_argument("--serve-http-fail-migration", action="store_true",
                    help="--serve-http: refuse POST /v1/import (chaos "
                    "knob for the kill-mid-migration soak schedules: an "
                    "importer that refuses must leave both pools "
                    "byte-identical — the gateway retries cold)")
    ap.add_argument("--serve-http-tls-cert", default=None, metavar="PEM",
                    help="--serve-http: serve the replica endpoint over "
                    "HTTPS with this certificate (pair with "
                    "--serve-http-tls-key; the gateway points "
                    "--replica-tls-ca at the CA bundle).  Omit both for "
                    "plain HTTP (loopback / single-tenant)")
    ap.add_argument("--serve-http-tls-key", default=None, metavar="PEM",
                    help="PEM private key for --serve-http-tls-cert")
    ap.add_argument("--page-size", type=int, default=None,
                    help="paged serving: KV page rows (must divide "
                    "--prompt-len).  Default: one page spans the prompt "
                    "pad (128 when it divides).  Set it SMALLER to seal "
                    "multi-turn decode chains — a retired stream seals "
                    "only FULL pages")
    ap.add_argument("--sample-temperature", type=float, default=0.0,
                    help="decode: sample with this temperature instead "
                    "of greedy argmax (0 = greedy).  With --serving "
                    "speculative the batcher runs LOSSLESS rejection-"
                    "sampled speculation: accepted drafts are exact "
                    "target-distribution samples")
    ap.add_argument("--sample-top-k", type=int, default=0,
                    help="decode --sample-temperature: truncate sampling "
                    "to the k most likely tokens (0 = full softmax)")
    ap.add_argument("--sample-seed", type=int, default=0,
                    help="decode --sample-temperature: base seed for "
                    "seed-pinned sampling — request i pins seed+i, so "
                    "sampled streams reproduce across reruns, replicas, "
                    "slots, and batch compositions")
    ap.add_argument("--serve-fp32", action="store_true",
                    help="serve float32 weights instead of the bf16 "
                    "cast: exact cross-process greedy determinism (the "
                    "decode-page-cache 'fp32' sharing class; the "
                    "multi-process dryruns gate token identity on it). "
                    "Costs 2x parameter HBM — tiny-shape smoke and "
                    "numerics-sensitive deployments only")
    ap.add_argument("--serve-http-auth-token-file", default=None,
                    metavar="FILE",
                    help="--serve-http: require 'Authorization: Bearer "
                    "<token>' (file contents) on every /v1/* verb — "
                    "submit/cancel/export/import/state move KV bytes "
                    "and cancel sequences; /healthz and /metrics stay "
                    "open for probes and scrapes")
    ap.add_argument("--prompt-len", type=int, default=32,
                    help="decode: prompt tokens per request (prompt-len + "
                    "--steps must fit --seq + 1, the lm family's cache size)")
    ap.add_argument("--int8", action="store_true",
                    help="decode: serve weight-only int8 (per-output-"
                    "channel scales; halves the per-step parameter stream)")
    ap.add_argument("--serving",
                    choices=["static", "continuous", "paged", "speculative"],
                    default="static",
                    help="decode execution strategy: static = aligned-batch "
                    "greedy (default); continuous = slot-based continuous "
                    "batching (models/serving.py); paged = continuous "
                    "batching over a shared KV page pool (models/paging.py); "
                    "speculative = draft-verified continuous batching "
                    "(models/spec_serving.py); paged and speculative both "
                    "support sampled (rejection-verified) speculation when "
                    "--sample-temperature > 0")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculative: proposals per verify chunk")
    ap.add_argument("--draft-layers", type=int, default=1,
                    help="speculative: draft depth")
    ap.add_argument("--draft-hidden", type=int, default=0,
                    help="speculative: draft width (0 = hidden/4)")
    ap.add_argument("--speculate", action="store_true",
                    help="paged serving: draft-then-verify multi-token "
                    "decode through the page pool (--spec-k deep; OFF by "
                    "default — greedy-lossless, so output is identical "
                    "either way)")
    from kubegpu_tpu.models.serving import (
        DECODE_PAGE_CACHE_POLICIES,
        KV_DTYPES,
    )

    ap.add_argument("--decode-page-cache", default="off",
                    choices=list(DECODE_PAGE_CACHE_POLICIES),
                    help="paged serving: seal retired sequences' "
                    "DECODE-produced pages into the shared prefix cache "
                    "so a session's turn 2 skips re-prefilling turn 1's "
                    "output (session KV reuse).  off = prompt pages only "
                    "(default); fp32 = share only when serving float32 "
                    "full-width (property-tested greedy-token-identical); "
                    "quantized = share only on an int8 pool (--kv-dtype "
                    "int8; deterministic in-mode, agreement measured); "
                    "all = any dtype (bf16 may flip near-tie argmaxes — "
                    "drift is measured in bench.py serving_multiturn)")
    ap.add_argument("--kv-dtype", default=None,
                    choices=list(KV_DTYPES),
                    help="paged serving: KV page-pool STORAGE format.  "
                    "Default: full width at the serving dtype.  int8 = "
                    "per-page per-head-scaled symmetric int8 pages (the "
                    "paged kernels dequantize in-kernel; sealing "
                    "requantizes to tight scales) — half the resting "
                    "pool bytes, ~2x the pool rows per byte budget, and "
                    "half the migration wire bytes per page; quality is "
                    "MEASURED by bench.py serving_quantized_pool.  "
                    "bf16/fp32 must match the serving dtype (an "
                    "explicit full-width declaration)")
    ap.add_argument(
        "--draft-ckpt-dir", default="",
        help="orbax checkpoint root for the DRAFT model "
        "(<dir>/lm layout, like --ckpt-dir); empty = fresh-init draft",
    )
    ap.add_argument(
        "--ckpt-dir",
        default=os.environ.get("KUBEGPU_CKPT_DIR", ""),
        help="checkpoint/resume root (shared across the gang); empty disables. "
        "Checkpoints are written under <dir>/<model> so variants with "
        "different param layouts (e.g. resnet50 vs resnet50-unrolled) "
        "never collide on resume",
    )
    ap.add_argument("--ckpt-every", type=int, default=10, help="steps between saves")
    ap.add_argument(
        "--data",
        default="synthetic",
        choices=["synthetic", "stream", "resident"],
        help="synthetic = device-resident pool of distinct batches "
        "(default: real data variation, zero per-step H2D); stream = "
        "double-buffered host->device pipeline (the shape for real "
        "loaders); resident = one constant batch (pure-step microbench)",
    )
    ap.add_argument(
        "--data-pool",
        type=int,
        default=8,
        help="synthetic mode: number of distinct device-resident batches "
        "to cycle",
    )
    ap.add_argument(
        "--compile-cache",
        default=os.environ.get("KUBEGPU_TPU_COMPILE_CACHE", ""),
        help="persistent XLA compilation cache dir (pre-seed it in the pod "
        "image or mount it from a PD to take compiles off the "
        "schedule-to-first-step path); empty disables",
    )
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    t0 = time.monotonic()
    initialize_distributed()

    import jax

    # Honor JAX_PLATFORMS even when a sitecustomize imported jax (and
    # pinned a TPU platform) at interpreter start: the config route works
    # until the first backend query, env vars alone may not (same dance as
    # tests/conftest.py — without this, `JAX_PLATFORMS=cpu worker ...`
    # silently lands on the chip).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    if args.compile_cache:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.abspath(args.compile_cache)
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)

    log.info(
        "devices: %d global / %d local (%s), visible_chips=%s, model=%s",
        jax.device_count(),
        jax.local_device_count(),
        jax.devices()[0].platform,
        os.environ.get("TPU_VISIBLE_CHIPS", "<unset>"),
        args.model,
    )
    if args.model in RESNET_MODELS:
        return _run_resnet(args, t0)
    if args.model in LM_MODELS:
        return _run_lm_family(args, t0)
    if args.model == "decode":
        return _run_decode(args, t0)
    return _run_pp(args, t0)


if __name__ == "__main__":
    raise SystemExit(main())
