"""Training-worker entrypoint for the sample pod specs (SURVEY.md §3.4).

This is the process that runs *inside* the scheduled containers — the
workload side of the framework's env contract.  It consumes exactly what the
CRI shim injects (crishim/inject.py):

    TPU_VISIBLE_CHIPS        which host chips this container may claim
    TPU_WORKER_ID            this worker's index in the gang
    JAX_COORDINATOR_ADDRESS  worker 0's host:port for jax.distributed
    JAX_NUM_PROCESSES        gang size
    JAX_PROCESS_ID           == TPU_WORKER_ID

and runs data-parallel ResNet-50 training steps under pjit over a
``("data",)`` mesh spanning the gang's chips, printing the pod-visible half
of the north-star metric: time from process start to the first completed
optimizer step (BASELINE.json: schedule-to-first-step < 60 s).

Single-worker mode (JAX_NUM_PROCESSES absent or 1) skips the distributed
rendezvous, so the same image serves BASELINE configs 2-5.

    python -m kubegpu_tpu.models.worker --steps 20 --batch-per-chip 32
"""

from __future__ import annotations

import argparse
import logging
import os
import time

log = logging.getLogger("kubegpu_tpu.worker")


def initialize_distributed() -> None:
    """jax.distributed rendezvous from the injected env; no-op when solo."""
    import jax

    num = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
    if num <= 1:
        return
    coordinator = os.environ["JAX_COORDINATOR_ADDRESS"]
    pid = int(os.environ.get("JAX_PROCESS_ID", os.environ.get("TPU_WORKER_ID", "0")))
    log.info("jax.distributed.initialize(%s, num=%d, id=%d)", coordinator, num, pid)
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=num, process_id=pid
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--model",
        default="resnet50",
        choices=["resnet50", "resnet50-unrolled", "resnet-tiny"],
        help="resnet50 = scan-rolled flagship (fast compile); "
        "resnet50-unrolled = plain per-block variant",
    )
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-per-chip", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument(
        "--ckpt-dir",
        default=os.environ.get("KUBEGPU_CKPT_DIR", ""),
        help="checkpoint/resume root (shared across the gang); empty disables. "
        "Checkpoints are written under <dir>/<model> so variants with "
        "different param layouts (e.g. resnet50 vs resnet50-unrolled) "
        "never collide on resume",
    )
    ap.add_argument("--ckpt-every", type=int, default=10, help="steps between saves")
    ap.add_argument(
        "--data",
        default="synthetic",
        choices=["synthetic", "stream", "resident"],
        help="synthetic = device-resident pool of distinct batches "
        "(default: real data variation, zero per-step H2D); stream = "
        "double-buffered host->device pipeline (the shape for real "
        "loaders); resident = one constant batch (pure-step microbench)",
    )
    ap.add_argument(
        "--data-pool",
        type=int,
        default=8,
        help="synthetic mode: number of distinct device-resident batches "
        "to cycle",
    )
    ap.add_argument(
        "--compile-cache",
        default=os.environ.get("KUBEGPU_TPU_COMPILE_CACHE", ""),
        help="persistent XLA compilation cache dir (pre-seed it in the pod "
        "image or mount it from a PD to take compiles off the "
        "schedule-to-first-step path); empty disables",
    )
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    t0 = time.monotonic()
    initialize_distributed()

    import jax
    import jax.numpy as jnp

    if args.compile_cache:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.abspath(args.compile_cache)
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)

    from kubegpu_tpu.models import (
        ResNet,
        ResNet50,
        ScanResNet50,
        create_train_state,
        make_resnet_train_step,
        place_resnet,
    )
    from kubegpu_tpu.parallel import device_mesh

    n = jax.device_count()
    log.info(
        "devices: %d global / %d local (%s), visible_chips=%s",
        n,
        jax.local_device_count(),
        jax.devices()[0].platform,
        os.environ.get("TPU_VISIBLE_CHIPS", "<unset>"),
    )
    mesh = device_mesh({"data": n})
    if args.model == "resnet50":
        model = ScanResNet50(num_classes=args.num_classes)
        size = args.image_size
    elif args.model == "resnet50-unrolled":
        model = ResNet50(num_classes=args.num_classes)
        size = args.image_size
    else:  # CI-sized twin, same code path
        model = ResNet(stage_sizes=(1, 1, 1, 1), num_filters=8, num_classes=10)
        size = 32

    from kubegpu_tpu.models.data import (
        device_pool_batches,
        prefetch_to_device,
        synthetic_image_batches,
    )
    from kubegpu_tpu.parallel.sharding import batch_sharding

    batch = args.batch_per_chip * n
    rng = jax.random.PRNGKey(0)
    # input pipeline: each process generates ONLY its local rows of the
    # global batch (put_global assembles the global array), seeded by the
    # same id chain the rendezvous uses so gang workers draw disjoint
    # streams however the env named them
    worker_id = int(
        os.environ.get("JAX_PROCESS_ID", os.environ.get("TPU_WORKER_ID", "0"))
        or 0
    )
    local_batch = args.batch_per_chip * jax.local_device_count()
    source = synthetic_image_batches(
        local_batch, size=size, num_classes=args.num_classes, worker_id=worker_id
    )
    if args.data == "synthetic":
        batches = device_pool_batches(
            source, batch_sharding(mesh), pool=max(args.data_pool, 1)
        )
        images, labels = next(batches)
    elif args.data == "stream":
        batches = prefetch_to_device(source, batch_sharding(mesh), depth=2)
        images, labels = next(batches)
    else:  # resident: one constant device batch, no pipeline
        images = jnp.ones((batch, size, size, 3), jnp.float32)
        labels = jnp.zeros((batch,), jnp.int32)
        batches = None
    state = create_train_state(model, rng, images)
    state, images, labels = place_resnet(state, (images, labels), mesh)
    step = make_resnet_train_step(mesh)

    mgr = None
    start_step = 0
    save_checkpoint = None
    if args.ckpt_dir:
        from kubegpu_tpu.models.checkpoint import (
            make_manager,
            restore_checkpoint,
            save_checkpoint,
        )

        root = os.path.abspath(args.ckpt_dir)
        try:
            legacy = sorted(
                d for d in os.listdir(root)
                if d.isdigit() and os.path.isdir(os.path.join(root, d))
            )
        except OSError:
            legacy = []
        if legacy:
            # checkpoints written before per-model namespacing live at the
            # root; their param layout may not match this model variant, so
            # they are NOT restored — but silence here would look like a
            # silent restart from step 0
            log.warning(
                "ignoring legacy checkpoints at %s (steps %s); checkpoints "
                "now live under %s — restore manually if the layouts match",
                root, ",".join(legacy), os.path.join(root, args.model),
            )
        mgr = make_manager(os.path.join(root, args.model))
        restored = restore_checkpoint(mgr, state)
        if restored is not None:
            state = restored
            start_step = int(jax.device_get(state.step))
            print(f"RESUMED step={start_step}", flush=True)

    state, loss = step(state, images, labels)
    jax.block_until_ready(loss)
    first_step_s = time.monotonic() - t0
    # the string the e2e latency probe (and a human) greps for
    print(f"FIRST_STEP_DONE seconds={first_step_s:.2f} loss={float(loss):.4f}", flush=True)

    t1 = time.monotonic()
    save_s = 0.0
    done = start_step + 1
    last_saved = -1
    for _ in range(args.steps - 1):
        if batches is not None:
            images, labels = next(batches)  # prefetched: already on device
        state, loss = step(state, images, labels)
        done += 1
        if mgr is not None and args.ckpt_every > 0 and done % args.ckpt_every == 0:
            # periodic crash-recovery saves; excluded from the throughput
            # metric so checkpointed and plain runs stay comparable
            ts = time.monotonic()
            last_saved = save_checkpoint(mgr, state)
            save_s += time.monotonic() - ts
    jax.block_until_ready(loss)
    dt = time.monotonic() - t1 - save_s
    if args.steps > 1:
        ips = batch * (args.steps - 1) / dt
        print(f"steady_state images_per_sec={ips:.1f} loss={float(loss):.4f}", flush=True)
    if mgr is not None:
        final_step = int(jax.device_get(state.step))
        if final_step != last_saved:  # orbax raises on duplicate-step saves
            save_checkpoint(mgr, state)
        mgr.wait_until_finished()
        print(f"CHECKPOINT_SAVED step={final_step}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
