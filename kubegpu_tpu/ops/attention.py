"""Attention ops: Pallas flash attention + ring attention (context parallel).

The hot-op layer of the workload stack (the reference framework schedules
long-context jobs; this is what those jobs run).  Two TPU-first designs:

- :func:`flash_attention` — a Pallas TPU kernel (pallas_guide.md patterns):
  online-softmax over K/V blocks so the s×s score matrix never exists in
  HBM; grid (batch*heads, q-blocks, k-blocks) with the sequential innermost
  grid dimension carrying running max/denominator in VMEM scratch; causal
  upper-triangle blocks are skipped outright (half the FLOPs).  MXU-shaped:
  128-lane blocks, f32 accumulation via preferred_element_type.  The
  backward is Pallas too (O(seq) memory end to end): dK/dV and dQ kernels
  recompute p blockwise from the forward's saved logsumexp, so only
  out+lse ride HBM as residuals — rematerialisation, the standard TPU
  trade.

- :func:`ring_attention` — sequence/context parallelism over a mesh axis:
  each device owns a query shard, K/V shards rotate around the ring via
  ``jax.lax.ppermute`` (XLA lowers to ICI neighbour exchange), partial
  attention folded with the same online-softmax algebra.  Communication
  overlaps compute naturally (one hop per step), memory per chip is
  O(seq/ring).  Use inside ``shard_map``; :func:`ring_attention_sharded`
  wraps that for convenience.

Shapes are BSHD: (batch, seq, heads, head_dim) throughout.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from kubegpu_tpu.parallel.sharding import shard_map_compat

NEG_INF = float("-inf")


# ---------------------------------------------------------------------------
# Reference implementation (the numerics oracle; also the VJP recompute path)
# ---------------------------------------------------------------------------

def reference_attention(q, k, v, causal: bool = True):
    """Plain einsum attention in f32 — O(s^2) memory, used for testing and
    as the recompute body of flash_attention's backward."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(d)
    if causal:
        # global positions: q row i attends to k col j iff j <= i + (sk - sq)
        # (the offset form supports sq != sk, e.g. ring attention shards)
        qi = jnp.arange(sq)[:, None]
        kj = jnp.arange(sk)[None, :]
        scores = jnp.where(kj <= qi + (sk - sq), scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash attention
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, sm_scale: float, causal: bool, block_q: int, block_k: int,
                  seq_k: Optional[int]):
    """One (bh, qi, ki) grid step: fold K/V block ki into the running
    softmax state for query block qi.  TPU iterates the last grid dim
    sequentially, so m/l/acc scratch persists across ki for a fixed qi."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    # causal: skip blocks strictly above the diagonal band
    @pl.when(jnp.logical_not(causal) | (k_start <= q_start + block_q - 1))
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale                               # (block_q, block_k)
        if causal or seq_k is not None:
            rows = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) + k_start
            valid = jnp.ones(scores.shape, bool)
            if causal:
                valid &= cols <= rows
            if seq_k is not None:                  # padded K tail is invalid
                valid &= cols < seq_k
            scores = jnp.where(valid, scores, NEG_INF)

        m_prev = m_ref[:, :1]                      # (block_q, 1), lane-replicated
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # m_new is -inf only on fully-masked rows; exp(scores - -inf) -> nan,
        # guard with a zero-safe shift
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - shift)                # (block_q, block_k)
        correction = jnp.where(
            jnp.isfinite(m_prev), jnp.exp(m_prev - shift), 0.0
        )                                          # (block_q, 1)
        l_new = correction * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        denom = jnp.where(l == 0.0, 1.0, l)        # fully-masked row -> 0 output
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # logsumexp per row, the backward's only softmax residual
        # (fully-masked rows stay -inf; the backward masks them out).
        # Stored (bh, nq, 8, block_q): block_q rides the 128-divisible
        # minor dim and the 8 merely fills one sublane tile — 16x smaller
        # than the lane-replicated (…, 128) layout for the same
        # TPU-layout-legality
        m = m_ref[:, :1]
        lse = jnp.where(
            l > 0.0, jnp.where(jnp.isfinite(m), m, 0.0) + jnp.log(denom), NEG_INF
        )
        lse_ref[0, 0] = jnp.broadcast_to(lse[:, 0][None, :], lse_ref.shape[2:])


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: Optional[bool]):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if causal and sq != sk:
        raise ValueError(f"causal flash requires sq == sk, got ({sq}, {sk})")
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # arbitrary lengths: pad to block multiples; padded K columns are masked
    # inside the kernel, padded Q rows are sliced off the output
    pad_q = -sq % block_q
    pad_k = -sk % block_k
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    sqp, skp = sq + pad_q, sk + pad_k
    sm_scale = 1.0 / math.sqrt(d)

    # fold heads into the leading grid dim: (b, s, h, d) -> (b*h, s, d)
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    # under shard_map with vma checking, pallas outputs must declare which
    # mesh axes they vary over: the join of the inputs'
    vma = _vma_join(qf, kf, vf)
    grid = (b * h, sqp // block_q, skp // block_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=sk if pad_k else None,
    )
    of, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda bh, qi, ki: (bh, qi, 0, 0)),
        ],
        out_shape=[
            _sds((b * h, sqp, d), q.dtype, vma),
            _sds((b * h, sqp // block_q, 8, block_q), jnp.float32, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max (lane-replicated)
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denominator
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf)
    out = of.reshape(b, h, sqp, d)[:, :, :sq].transpose(0, 2, 1, 3)
    return out, lse


def _bwd_block(q, k, v, do, o, lse_row, sm_scale, valid):
    """Shared per-block backward algebra: recompute p from (q,k,lse), then
    ds = p * (dO·Vᵀ - delta) * scale with delta = rowsum(dO·O) computed
    right here from the resident blocks (materializing it in HBM would add
    a seq-length array for an elementwise product the VPU does for free).
    All f32, MXU dot_generals."""
    delta_col = jnp.sum(do * o, axis=-1, keepdims=True)  # (block_q, 1)
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale                                   # (block_q, block_k)
    # rows with lse=-inf are fully masked (or padding): p must be 0, and
    # exp(score - -inf) would be nan — guard the shift.  Shapes stay 2D
    # throughout: Mosaic only supports minor-dim insertion on 32-bit
    # values, so the bool mask must be DERIVED in 2D, never reshaped to it
    lse_col = lse_row[:, None]                     # f32 (block_q, 1)
    finite = jnp.isfinite(lse_col)
    shift = jnp.where(finite, lse_col, 0.0)
    p = jnp.where(
        valid & finite, jnp.exp(scores - shift), 0.0
    )                                              # (block_q, block_k)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                              # (block_q, block_k)
    ds = p * (dp - delta_col) * sm_scale
    return p, ds


def _flash_bwd_dkdv_kernel(q_ref, do_ref, o_ref, lse_ref, k_ref, v_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc,
                           *, sm_scale, causal, block_q, block_k,
                           seq_q, seq_k):
    """Grid (bh, ki, qi), qi innermost-sequential: accumulate this K/V
    block's gradients over all query blocks in VMEM scratch."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    # causal: a q block contributes iff its rows reach these k columns
    @pl.when(jnp.logical_not(causal) | (q_start + block_q - 1 >= k_start))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + q_start
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + k_start
        valid = (rows < seq_q) & (cols < seq_k)
        if causal:
            valid &= cols <= rows
        p, ds = _bwd_block(
            q, k, v, do, o, lse_ref[0, 0, 0], sm_scale, valid
        )
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                          # pᵀ·dO (block_k, d)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                          # dsᵀ·q (block_k, d)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, do_ref, o_ref, lse_ref, k_ref, v_ref,
                         dq_ref, dq_acc,
                         *, sm_scale, causal, block_q, block_k,
                         seq_q, seq_k):
    """Grid (bh, qi, ki), ki innermost-sequential: accumulate this query
    block's gradient over all K/V blocks."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(jnp.logical_not(causal) | (k_start <= q_start + block_q - 1))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + q_start
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + k_start
        valid = (rows < seq_q) & (cols < seq_k)
        if causal:
            valid &= cols <= rows
        _, ds = _bwd_block(
            q, k, v, do, o, lse_ref[0, 0, 0], sm_scale, valid
        )
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                          # ds·k (block_q, d)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k, interpret):
    """Pallas flash backward: O(seq) memory — recomputes p blockwise from
    the saved logsumexp instead of materializing the s×s score matrix
    (which the old XLA-recompute backward did, defeating flash's point for
    long sequences)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    pad_q = -sq % block_q
    pad_k = -sk % block_k
    sm_scale = 1.0 / math.sqrt(d)

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        g = jnp.pad(g, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    sqp, skp = sq + pad_q, sk + pad_k
    qf, kf, vf, dof, of = fold(q), fold(k), fold(v), fold(g), fold(out)
    # lse from the forward is (b*h, nq, 8, block_q); forward and backward
    # share block_q so the padded lengths agree.  delta = rowsum(dO*O) is
    # computed inside the kernels from the resident blocks — no HBM array.

    bh = b * h
    nq, nk = sqp // block_q, skp // block_k
    vma = _vma_join(qf, kf, vf, dof, of, lse)
    qspec3 = pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0))
    lspec = pl.BlockSpec((1, 1, 8, block_q), lambda bhi, ki, qi: (bhi, qi, 0, 0))
    kspec3 = pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_q=sq, seq_k=sk,
        ),
        grid=(bh, nk, nq),
        in_specs=[qspec3, qspec3, qspec3, lspec, kspec3, kspec3],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        ],
        out_shape=[
            _sds((bh, skp, d), k.dtype, vma),
            _sds((bh, skp, d), v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, dof, of, lse, kf, vf)

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_q=sq, seq_k=sk,
        ),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda bhi, qi, ki: (bhi, qi, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
        out_shape=_sds((bh, sqp, d), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, dof, of, lse, kf, vf)

    def unfold(x, s, orig):
        return x.reshape(b, h, s, d)[:, :, :orig].transpose(0, 2, 1, 3)

    return unfold(dq, sqp, sq), unfold(dk, skp, sk), unfold(dv, skp, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 1024,
                    block_k: int = 1024, interpret: Optional[bool] = None):
    """Flash attention, BSHD.  O(seq) memory in BOTH directions: the
    forward keeps only out + logsumexp; the backward recomputes scores
    blockwise in its own Pallas kernels.

    Default blocks are large (1024x1024).  Small blocks pay grid overhead
    and starve the MXU: the r3 sweep measured 128x128 at 2.6x slower
    than 512x1024 (60.5 vs 23.9 ms, b16 h32 s1024 d128 fwd+bwd, on the
    r3-era backward kernels).  The r5 sweep — after the backward-kernel
    improvements that brought that config to ~8 ms — moved block_q
    512 -> 1024 for another measured win at BOTH scales (b16 h32 s1024:
    ~8 -> 5.8 ms; b1 h16 s16384: ~30 -> 24.9 ms per fwd+bwd; 2048-wide
    blocks fail to compile — VMEM).  The VMEM residency at d<=128 stays
    a few MB; shorter sequences clamp via min(block, seq) as always."""
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(
        q, k, v, out, lse, g, causal, block_q, block_k, interpret
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# lse layout helpers (between the packed kernel layout and a dense vector)
# ---------------------------------------------------------------------------

def _lse_unpack(packed, b, h, s, block_q):
    """Kernel lse layout (b*h, s//block_q, 8, block_q) -> dense (b, s, h)
    f32 (BSH, matching the BSHD tensors it normalizes)."""
    dense = packed[:, :, 0, :].reshape(b, h, s)
    return dense.transpose(0, 2, 1)


def _lse_pack(dense, block_q):
    """Dense (b, s, h) f32 -> the kernel layout the backward kernels read."""
    b, s, h = dense.shape
    x = dense.transpose(0, 2, 1).reshape(b * h, s // block_q, 1, block_q)
    return jnp.broadcast_to(x, (b * h, s // block_q, 8, block_q))


def _vma_join(*arrays):
    """The union of the arrays' shard_map varying-axes (vma) types, or
    None on jax versions without vma typing (0.4.x — where
    ``shard_map_compat`` disables replication checking, so no
    declaration is needed)."""
    tof = getattr(jax, "typeof", None)
    if tof is None:
        return None
    vma = frozenset()
    for a in arrays:
        vma = vma | tof(a).vma
    return vma


def _sds(shape, dtype, vma):
    """``jax.ShapeDtypeStruct`` carrying a vma declaration when the
    running jax supports one (``_vma_join`` returned a set)."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _stamp(x, *refs):
    """Add a zero derived from `refs` so `x` carries their shard_map
    varying-axes (vma) type.  Constant/zero initializers are device-invariant
    by construction; folding them with per-device values needs the vma sets
    to agree, whatever mesh axes the inputs vary over."""
    z = jnp.zeros((), x.dtype)
    for r in refs:
        z = z + jnp.sum(r).astype(x.dtype) * 0
    return x + z


# ---------------------------------------------------------------------------
# Ring attention (context parallelism over a mesh axis)
# ---------------------------------------------------------------------------

def _ring_attention_einsum(q, k, v, axis_name: str, causal: bool = True):
    """Einsum-bodied ring attention — the fallback path for shard shapes the
    Pallas blocks can't tile (see :func:`ring_attention`), and the numerics
    oracle for the flash-bodied ring.  O(s_loc^2) f32 transient per step."""
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % size) for i in range(size)]

    qf = q.astype(jnp.float32)
    q_pos = my * s_loc + jnp.arange(s_loc)                 # global q rows

    def fold_block(o, m, l, k_cur, v_cur, step):
        """Fold one resident K/V block into the online-softmax state."""
        src = (my - step) % size                            # owner of k_cur
        k_pos = src * s_loc + jnp.arange(s_loc)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32)
        ) * sm_scale
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]         # (s_loc, s_loc) global
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        m_cur = jnp.max(scores, axis=-1)                    # (b, h, q)
        m_new = jnp.maximum(m, m_cur)
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - shift[..., None])
        correction = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
        l_new = correction * l + jnp.sum(p, axis=-1)
        o_new = o * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        return o_new, m_new, l_new

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        o, m, l = fold_block(o, m, l, k_cur, v_cur, step)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    # constants are device-invariant to shard_map's varying-axes typing, but
    # the folded carries vary over every axis q varies over (the ring axis,
    # plus any batch axis of a DP x CP mesh) — _stamp marks exactly that set
    # onto the initializers, whatever the mesh
    o0 = _stamp(jnp.zeros((b, h, s_loc, d), jnp.float32), qf)
    m0 = _stamp(jnp.full((b, h, s_loc), NEG_INF, jnp.float32), qf)
    l0 = _stamp(jnp.zeros((b, h, s_loc), jnp.float32), qf)
    # scan rotates size-1 times; the last resident block folds outside so no
    # dead final exchange is issued (2*(size-1) hops total, as documented)
    (o, m, l, k_last, v_last), _ = jax.lax.scan(
        body, (o0, m0, l0, k, v), jnp.arange(size - 1)
    )
    o, m, l = fold_block(o, m, l, k_last, v_last, size - 1)
    denom = jnp.where(l == 0.0, 1.0, l)
    out = (o / denom[..., None]).transpose(0, 2, 1, 3)      # -> (b, s, h, d)
    return out.astype(q.dtype)


def _ring_block_sizes(s_loc: int) -> Optional[tuple]:
    """Pallas block sizes for a ring shard, or None when the shard can't be
    tiled without padding (padding inside the ring would corrupt the global
    position bookkeeping — those shapes take the einsum fallback).  Prefers
    large blocks (same v5e measurement as flash_attention's defaults)."""
    if s_loc <= 128 or (s_loc <= 512 and s_loc % 128 == 0):
        # single whole-shard block: array-equal block dims are always
        # mosaic-legal, and one big block beats tiling at these sizes
        return s_loc, s_loc
    for b in (512, 256, 128):
        if s_loc % b == 0:
            return b, b
    return None


def _ring_causal_dispatch(my, size, step, causal,
                          run_unmasked, run_causal, skip, operands):
    """Shared forward/backward dispatch for one ring step: which masking
    does the resident block (owner ``src = (my - step) % size``) need?
    Globally-causal means: src < my → fully visible (unmasked), src == my →
    the causal diagonal, src > my → fully masked (skip).  One helper so the
    subtle idx→branch mapping can never desynchronize between the two
    passes."""
    if not causal:
        return run_unmasked(operands)
    src = (my - step) % size
    idx = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
    return jax.lax.switch(idx, (run_unmasked, run_causal, skip), operands)


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k):
    """Forward ring pass with the Pallas flash kernel as the per-block body.

    Each resident K/V block is one flash_forward call (causal on the
    diagonal step, unmasked on fully-visible steps, skipped on fully-masked
    steps — lax.switch on the device-varying block owner, so each core only
    runs the kernel it needs); partial (out, lse) pairs fold with the
    logsumexp algebra.  Per-chip memory O(s_loc): no (s_loc, s_loc) array
    ever exists outside VMEM."""
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    perm = [(i, (i + 1) % size) for i in range(size)]

    def block_partial(k_cur, v_cur, step):
        def run(causal_flag):
            def f(kv):
                o, lse_p = _flash_forward(
                    q, kv[0], kv[1], causal_flag, block_q, block_k, None
                )
                return (
                    o.astype(jnp.float32),
                    _lse_unpack(lse_p, b, h, s_loc, block_q),
                )
            return f

        def skip(kv):
            o = _stamp(jnp.zeros((b, s_loc, h, d), jnp.float32), q, kv[0], kv[1])
            lse = _stamp(jnp.full((b, s_loc, h), NEG_INF, jnp.float32),
                         q, kv[0], kv[1])
            return o, lse

        return _ring_causal_dispatch(
            my, size, step, causal, run(False), run(True), skip, (k_cur, v_cur)
        )

    def fold(o_acc, lse_acc, o_blk, lse_blk):
        lse_new = jnp.logaddexp(lse_acc, lse_blk)
        shift = jnp.where(jnp.isfinite(lse_new), lse_new, 0.0)
        w_acc = jnp.where(jnp.isfinite(lse_acc), jnp.exp(lse_acc - shift), 0.0)
        w_blk = jnp.where(jnp.isfinite(lse_blk), jnp.exp(lse_blk - shift), 0.0)
        o_new = o_acc * w_acc[..., None] + o_blk * w_blk[..., None]
        return o_new, lse_new

    o0 = _stamp(jnp.zeros((b, s_loc, h, d), jnp.float32), q, k, v)
    lse0 = _stamp(jnp.full((b, s_loc, h), NEG_INF, jnp.float32), q, k, v)

    def body(carry, step):
        o, lse, k_cur, v_cur = carry
        o, lse = fold(o, lse, *block_partial(k_cur, v_cur, step))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, lse, k_nxt, v_nxt), None

    (o, lse, k_last, v_last), _ = jax.lax.scan(
        body, (o0, lse0, k, v), jnp.arange(size - 1)
    )
    o, lse = fold(o, lse, *block_partial(k_last, v_last, size - 1))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_attention_flash(q, k, v, axis_name, causal, block_q, block_k):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, block_q, block_k):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, block_q, block_k, res, g):
    """Ring backward that RE-ROTATES K/V instead of saving per-step copies:
    residuals are only the local (q, k, v, out, lse).  dK/dV accumulators
    travel the ring alongside their blocks, so after `size` hops each block
    arrives home carrying every device's contribution; dQ accumulates
    locally.  Per-block gradients are the Pallas backward kernels, p
    recomputed from the GLOBAL lse (partial contributions need no
    renormalization)."""
    q, k, v, out, lse = res
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % size) for i in range(size)]
    lse_packed = _lse_pack(lse, block_q)

    def block_grads(k_cur, v_cur, step):
        def run(causal_flag):
            def f(kv):
                return _flash_backward(
                    q, kv[0], kv[1], out, lse_packed, g,
                    causal_flag, block_q, block_k, None,
                )
            return f

        def skip(kv):
            return (
                _stamp(jnp.zeros_like(q), kv[0], kv[1], out, g),
                _stamp(jnp.zeros_like(kv[0]), q, out, g),
                _stamp(jnp.zeros_like(kv[1]), q, out, g),
            )

        return _ring_causal_dispatch(
            my, size, step, causal, run(False), run(True), skip, (k_cur, v_cur)
        )

    dq0 = _stamp(jnp.zeros(q.shape, jnp.float32), q, k, v, g)
    dk0 = _stamp(jnp.zeros(k.shape, jnp.float32), q, k, v, g)
    dv0 = _stamp(jnp.zeros(v.shape, jnp.float32), q, k, v, g)

    rot = lambda x: jax.lax.ppermute(x, axis_name, perm)

    def body(carry, step):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        dq_c, dk_c, dv_c = block_grads(k_cur, v_cur, step)
        dq = dq + dq_c.astype(jnp.float32)
        dk_cur = dk_cur + dk_c.astype(jnp.float32)
        dv_cur = dv_cur + dv_c.astype(jnp.float32)
        # rotate the gradients WITH their blocks; after the full cycle each
        # dK/dV lands back on its block's owner
        return (rot(k_cur), rot(v_cur), rot(dk_cur), rot(dv_cur), dq), None

    (k_last, v_last, dk, dv, dq), _ = jax.lax.scan(
        body, (k, v, dk0, dv0, dq0), jnp.arange(size - 1)
    )
    # last block folds outside the scan so K/V skip their dead final hop;
    # only dK/dV need the homing exchange
    dq_c, dk_c, dv_c = block_grads(k_last, v_last, size - 1)
    dq = dq + dq_c.astype(jnp.float32)
    dk = rot(dk + dk_c.astype(jnp.float32))
    dv = rot(dv + dv_c.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   impl: str = "flash"):
    """Blockwise ring attention for sequence shards.  Call INSIDE shard_map:
    q/k/v are this device's (batch, seq_local, heads, head_dim) shards of a
    sequence sharded over `axis_name`; K/V rotate one ICI hop per step
    (``jax.lax.ppermute``) while each resident block folds into the running
    softmax state.

    Equivalent to full attention over the global sequence (causal masking
    uses global positions); comms 2·(ring-1) neighbour exchanges riding ICI.
    ``impl="flash"`` (default) runs each block through the Pallas flash
    kernel and a re-rotating custom VJP — O(s_loc) memory in BOTH
    directions; shard shapes the kernel can't tile (s_loc > 128 that is
    not a multiple of 128 — see _ring_block_sizes) fall back to
    ``impl="einsum"`` (O(s_loc^2) transient, still O(seq/ring)
    resident)."""
    if impl == "flash":
        bs = _ring_block_sizes(q.shape[1])
        if bs is not None and q.shape == k.shape:
            return _ring_attention_flash(q, k, v, axis_name, causal, *bs)
    return _ring_attention_einsum(q, k, v, axis_name, causal)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis: str, causal: bool = True,
                           batch_axis: Optional[str] = None,
                           heads_axis: Optional[str] = None,
                           impl: str = "flash"):
    """shard_map wrapper: q/k/v are GLOBAL (batch, seq, heads, head_dim)
    arrays; seq is sharded over `axis`; batch and heads may additionally be
    sharded over `batch_axis` / `heads_axis` (DP x TP x CP meshes) — the
    ring only ever talks along `axis`; attention is independent per batch
    row AND per head, so the other shards never communicate."""
    spec = P(batch_axis, axis, heads_axis, None)
    fn = shard_map_compat(
        functools.partial(ring_attention, axis_name=axis, causal=causal,
                          impl=impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses attention (all-to-all sequence parallelism)
# ---------------------------------------------------------------------------

def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      use_flash: bool = True):
    """All-to-all sequence parallelism (the Ulysses scheme) — the other
    long-context strategy next to :func:`ring_attention`.  Call INSIDE
    shard_map with (batch, seq_local, heads, head_dim) sequence shards:

    1. ``all_to_all`` re-shards seq→heads: each device ends up with the FULL
       sequence for ``heads/size`` heads (heads must divide by the axis
       size);
    2. attention runs entirely locally over the global sequence — no
       masking/softmax algebra across devices at all (vs ring's folded
       online softmax) — through the Pallas flash kernel by default, so
       per-device memory is O(seq), not O(seq^2) (``use_flash=False`` keeps
       the einsum oracle for testing);
    3. a second ``all_to_all`` re-shards heads→seq.

    Trade-off vs ring: 4 all-to-alls total (q,k,v in + out) but each is a
    single fused ICI collective with no per-step latency chain, and the
    compute is one dense local attention — usually the better choice when
    heads ≥ devices; ring wins when heads are few or seq shards are huge
    (its K/V resident set is O(seq/ring) vs Ulysses' O(seq))."""
    size = jax.lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    if h % size != 0:
        raise ValueError(
            f"ulysses needs the LOCAL (per-shard) head count ({h}) divisible "
            f"by the '{axis_name}' axis size ({size}); with TP-sharded heads "
            f"this is global_heads/tp — replicate heads over TP (heads_axis="
            f"None) or adjust the mesh"
        )
    # seq-shards -> head-shards: split heads (axis 2) across devices,
    # concatenate everyone's seq chunk (axis 1) in axis order = global order
    def scatter_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if use_flash:
        out = flash_attention(qg, kg, vg, causal)
    else:
        out = reference_attention(qg, kg, vg, causal)
    # head-shards -> seq-shards
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention_sharded(q, k, v, mesh: Mesh, axis: str,
                              causal: bool = True, use_flash: bool = True,
                              batch_axis: Optional[str] = None,
                              heads_axis: Optional[str] = None):
    """shard_map wrapper: q/k/v are GLOBAL (batch, seq, heads, head_dim)
    arrays; seq sharded over `axis`; batch/heads optionally over
    `batch_axis`/`heads_axis` (the LOCAL heads per TP shard must then
    still divide by the seq axis — ulysses' head-scatter works on the
    local head set)."""
    spec = P(batch_axis, axis, heads_axis, None)
    fn = shard_map_compat(
        functools.partial(
            ulysses_attention, axis_name=axis, causal=causal, use_flash=use_flash
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
