"""Hot ops for the TPU workload stack (pallas kernels + ring collectives)."""

from kubegpu_tpu.ops.attention import (
    flash_attention,
    reference_attention,
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
    ulysses_attention_sharded,
)
from kubegpu_tpu.ops.paged_attention import (
    paged_chunk_attention,
    paged_decode_attention,
    reference_paged_attention,
    reference_paged_chunk_attention,
)

__all__ = [
    "flash_attention",
    "paged_chunk_attention",
    "paged_decode_attention",
    "reference_paged_attention",
    "reference_paged_chunk_attention",
    "reference_attention",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
