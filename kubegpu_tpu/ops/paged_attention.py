"""Paged decode attention: one query token per slot over a shared KV pool.

The dense per-slot KV cache reserves ``max_seq`` rows per slot whether a
sequence uses them or not; serving mixes of short and long sequences
waste most of that HBM.  Paging shares one pool of fixed-size PAGES
across every slot: a per-slot PAGE TABLE maps logical cache pages to
physical pool pages, and attention walks the table (vLLM's PagedAttention,
built TPU-first).

Kernel shape: ``pltpu.PrefetchScalarGridSpec`` with the page table and
per-slot lengths as scalar-prefetch operands — BlockSpec index_maps read
the TABLE to pick which physical K/V page each grid step DMAs, so the
gather rides the normal pallas pipeline (no in-kernel dynamic indexing of
HBM).  Grid = (slot, logical page); the page dim is the sequential
innermost axis carrying the online-softmax state in VMEM scratch —
exactly the flash kernel's recipe (ops/attention.py) with pages instead
of contiguous K blocks.  Pages past a slot's length are masked out of the
compute AND their index_map re-points at the slot's LAST LIVE page: the
pallas pipeline elides the DMA when consecutive grid steps map to the
same block, so dead pages cost neither bandwidth nor compute — the
kernel's HBM traffic is O(live pages), which is the entire point of
paging.  Measured (v5e-1, r5, 8 slots x 32 heads, 3 of 16 pages live):
without the elision the kernel streamed the same bytes as the dense
cache and ran 0.56x dense; with it the kernel holds a stable 117-132 us
across every harness form while the dense twin measures 390-1,200 us
depending on microbench program form — i.e. >= 2.9x faster against the
FASTEST dense measurement (PARITY r5 has the form-sensitivity notes).

Layouts: pool pages are (heads, page_size, head_dim) — heads OUTERMOST,
so every in-kernel contraction is an elementwise-multiply + reduction
over a natural tile dim ((page_size, hd) tiles per head) and no
transposes or batched dots reach mosaic (which rejects dot_general batch
dims).  Decode attention at one token per slot is bandwidth-bound, so
the VPU formulation costs nothing against the MXU one.

Quantized pools: both kernels take optional ``k_scale``/``v_scale``
operands — (pool_pages, heads) float32, one symmetric scale per page
per head — and dequantize IN-KERNEL: the pool arrays then carry int8
and the kernel streams HALF the bytes per live page (the entire win of
an int8 pool on a bandwidth-bound kernel), multiplying each page block
by its per-head scale right after the f32 cast.  The scale blocks ride
the same table-indexed index_map as their pages, so dead pages' scale
DMAs are elided identically.  Being bandwidth-bound is also why the
dequant multiply is free.  (On real TPUs int8 VMEM tiles want
(32, 128) — size page_size x head_dim accordingly; the CPU sim's
interpret mode has no such constraint.)
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from kubegpu_tpu.parallel.sharding import MODEL_AXIS, shard_map_compat

NEG_INF = float("-inf")


def dequantize_pages(data: jax.Array, scale: jax.Array,
                     dtype=jnp.float32) -> jax.Array:
    """Expand a quantized pool to full width: ``data`` (P, h, page, hd)
    int8 times ``scale`` (P, h) broadcast over (page, hd).  The oracle
    half of the in-kernel dequant — property tests compare the
    quantized kernels against ``reference_paged_attention`` over THIS
    expansion, so the kernel's dequant math has an independent twin."""
    return (
        data.astype(jnp.float32) * scale[:, :, None, None]
    ).astype(dtype)


def quantize_pages(pages: jax.Array):
    """The inverse: per-page, per-head symmetric int8 quantization of
    full-width pages (n, h, page, hd) → (int8 data, (n, h) f32 scales).
    scale = amax/127 over each page's (page, hd) block per head; an
    all-zero block keeps scale 0 (dequantizes to exact zeros)."""
    f = pages.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=(2, 3))
    scale = amax / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    data = jnp.clip(
        jnp.round(f / safe[:, :, None, None]), -127, 127
    ).astype(jnp.int8)
    return data, scale


def reference_paged_attention(q, k_pool, v_pool, page_table, lengths):
    """Oracle: gather every slot's pages dense, run masked softmax
    attention.  q (b, h, hd); pools (pages, h, page, hd); (b, h, hd) out;
    f32 math like the kernel."""
    b, h, hd = q.shape
    n_pages = page_table.shape[1]
    page = k_pool.shape[2]
    # (b, n_pages, h, page, hd) -> (b, h, S, hd)
    k = jnp.moveaxis(k_pool[page_table], 1, 2).reshape(b, h, n_pages * page, hd)
    v = jnp.moveaxis(v_pool[page_table], 1, 2).reshape(b, h, n_pages * page, hd)
    scores = jnp.einsum(
        "bhd,bhsd->bhs", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(hd)
    cols = jnp.arange(n_pages * page)[None, None, :]
    scores = jnp.where(cols < lengths[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bhsd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def reference_paged_chunk_attention(q, k_pool, v_pool, page_table, lengths):
    """Oracle for the multi-query chunk kernel: q (b, L, h, hd); query row
    j attends cols < lengths[b] + j (intra-window causal — row j sees the
    window's earlier rows and itself, exactly the semantics a speculative
    verify chunk needs once all L rows' K/V are written).  f32 math."""
    b, L, h, hd = q.shape
    n_pages = page_table.shape[1]
    page = k_pool.shape[2]
    S = n_pages * page
    k = jnp.moveaxis(k_pool[page_table], 1, 2).reshape(b, h, S, hd)
    v = jnp.moveaxis(v_pool[page_table], 1, 2).reshape(b, h, S, hd)
    scores = jnp.einsum(
        "blhd,bhsd->bhls", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(hd)
    cols = jnp.arange(S)[None, None, None, :]
    lim = (lengths[:, None] + jnp.arange(L)[None, :])[:, None, :, None]
    scores = jnp.where(cols < lim, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhls,bhsd->blhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                  sm_scale: float, page: int, quant: bool = False):
    """One (slot, logical-page) grid step: fold this page into the slot's
    running softmax state.  The page dim is sequential, so m/l/acc
    scratch persists across it for a fixed slot.  ``quant`` inserts the
    per-page per-head scale refs after the pools and dequantizes the
    int8 page blocks right after their f32 cast — the rest of the fold
    is byte-identical to the full-width kernel."""
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b_i = pl.program_id(0)
    p_i = pl.program_id(1)
    n_p = pl.num_programs(1)

    @pl.when(p_i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b_i]

    # pages at/after the slot's length hold nothing attendable: skip the
    # compute (their DMA was elided too — kv_map aliases them to the last
    # live page, so the block ref holds stale-but-unread data)
    @pl.when(p_i * page < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # (h, hd)
        k = k_ref[0].astype(jnp.float32)               # (h, page, hd)
        v = v_ref[0].astype(jnp.float32)
        if quant:
            k = k * ks_ref[0][:, None, None]           # (h,) per-head scale
            v = v * vs_ref[0][:, None, None]
        # per-head scores without transposes or batched dots (mosaic
        # rejects dot_general batch dims): broadcast-multiply and reduce
        # the MINOR hd lanes -> (h, page)
        scores = jnp.sum(q[:, None, :] * k, axis=-1) * sm_scale
        cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) + p_i * page
        scores = jnp.where(cols < length, scores, NEG_INF)

        m_prev = m_ref[:, :1]                           # (h, 1)
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - shift)                     # (h, page)
        correction = jnp.where(
            jnp.isfinite(m_prev), jnp.exp(m_prev - shift), 0.0
        )
        l_ref[:] = jnp.broadcast_to(
            correction * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape,
        )
        # weighted V: (h, page, 1) * (h, page, hd) summed over page
        acc_ref[:] = acc_ref[:] * correction + jnp.sum(
            p[:, :, None] * v, axis=1
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p_i == n_p - 1)
    def _finalize():
        l = l_ref[:, :1]
        denom = jnp.where(l == 0.0, 1.0, l)             # length-0 slot -> 0s
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Single-token attention over paged KV for every slot.

    q: (b, h, hd); k_pool/v_pool: (n_pool_pages, h, page_size, hd);
    page_table: (b, n_pages) int32 physical page ids (tail entries may
    point anywhere valid — masked); lengths: (b,) int32 attendable rows.
    ``k_scale``/``v_scale`` (given together or not at all): the
    quantized pool's (n_pool_pages, h) per-page per-head scales — the
    pools then carry int8 and the kernel dequantizes in-VMEM (property-
    tested against dequantize-then-``reference_paged_attention``).
    Returns (b, h, hd) in q's dtype."""
    b, h, hd = q.shape
    _, hp, page, hdp = k_pool.shape
    assert (hp, hdp) == (h, hd), (k_pool.shape, q.shape)
    quant = k_scale is not None
    assert quant == (v_scale is not None), "scales come in k/v pairs"
    n_pages = page_table.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def kv_map(b_i, p_i, tbl, ln):
        # Dead pages (at/past the slot's live count) re-point at the last
        # live page: consecutive identical block indices skip the DMA in
        # the pallas pipeline, so only live pages are ever streamed.  The
        # compute for them is skipped in-kernel (pl.when), so WHAT they
        # alias is irrelevant — aliasing the last live page (not page 0)
        # keeps the index constant across every dead step of a slot.
        live_pages = jnp.maximum((ln[b_i] + page - 1) // page, 1)
        p_eff = jnp.minimum(p_i, live_pages - 1)
        return (tbl[b_i, p_eff], 0, 0, 0)

    def scale_map(b_i, p_i, tbl, ln):
        # the page's scale rides the same table walk (and the same
        # dead-page DMA elision) as its bytes
        live_pages = jnp.maximum((ln[b_i] + page - 1) // page, 1)
        p_eff = jnp.minimum(p_i, live_pages - 1)
        return (tbl[b_i, p_eff], 0)

    in_specs = [
        pl.BlockSpec((1, h, hd), lambda b_i, p_i, tbl, ln: (b_i, 0, 0)),
        pl.BlockSpec((1, h, page, hd), kv_map),
        pl.BlockSpec((1, h, page, hd), kv_map),
    ]
    operands = [q, k_pool, v_pool]
    if quant:
        in_specs += [
            pl.BlockSpec((1, h), scale_map),
            pl.BlockSpec((1, h), scale_map),
        ]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,   # page_table, lengths
        grid=(b, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, h, hd), lambda b_i, p_i, tbl, ln: (b_i, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),   # running max (lane-replicated)
            pltpu.VMEM((h, 128), jnp.float32),   # running denominator
            pltpu.VMEM((h, hd), jnp.float32),    # running numerator
        ],
    )
    return pl.pallas_call(
        partial(_paged_kernel, sm_scale=1.0 / math.sqrt(hd), page=page,
                quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, hd), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32), *operands)


# ---------------------------------------------------------------------------
# Tensor-parallel wrappers: heads sharded over the mesh's "model" axis.
#
# Every head's online-softmax walk is independent — no cross-head math
# anywhere in the kernels — so head-sharding is EXACT parallelism: each
# device runs the ordinary kernel on its h/tp local heads against its
# local 1/tp of every pool page's bytes, with the page table and lengths
# replicated.  No collective is issued here at all; the one all-reduce
# per transformer block lives in the row-parallel o_proj matmul that
# consumes this output (the Megatron discipline, parallel/sharding.py).
# GSPMD cannot partition a pallas_call on its own (it would replicate
# the POOL — the exact memory win paging exists for), hence shard_map.
# ---------------------------------------------------------------------------

def paged_decode_attention_sharded(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    mesh: Mesh,
    axis: str = MODEL_AXIS,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """``paged_decode_attention`` with the heads dim sharded over
    ``axis``: q (b, h, hd) and the pools (P, h, page, hd) carry h/tp
    local heads per device; table/lengths replicate.  Byte-identical to
    the unsharded kernel (per-head math is untouched).  A quantized
    pool's (P, h) scales shard their heads dim exactly like the pages
    they describe — per-head scales are per-head state."""
    if k_scale is None:
        fn = shard_map_compat(
            paged_decode_attention,
            mesh,
            in_specs=(
                P(None, axis, None), P(None, axis, None, None),
                P(None, axis, None, None), P(None, None), P(None),
            ),
            out_specs=P(None, axis, None),
        )
        return fn(q, k_pool, v_pool, page_table, lengths)

    def _quant(q_, kp, vp, tbl, ln, ks, vs):
        return paged_decode_attention(
            q_, kp, vp, tbl, ln, k_scale=ks, v_scale=vs
        )

    fn = shard_map_compat(
        _quant,
        mesh,
        in_specs=(
            P(None, axis, None), P(None, axis, None, None),
            P(None, axis, None, None), P(None, None), P(None),
            P(None, axis), P(None, axis),
        ),
        out_specs=P(None, axis, None),
    )
    return fn(q, k_pool, v_pool, page_table, lengths, k_scale, v_scale)


def paged_chunk_attention_sharded(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    mesh: Mesh,
    axis: str = MODEL_AXIS,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """``paged_chunk_attention`` (the speculative-verify multi-query
    kernel) head-sharded over ``axis``; same contract as the decode
    wrapper with q (b, L, h, hd), scales head-sharded like their
    pages."""
    if k_scale is None:
        fn = shard_map_compat(
            paged_chunk_attention,
            mesh,
            in_specs=(
                P(None, None, axis, None), P(None, axis, None, None),
                P(None, axis, None, None), P(None, None), P(None),
            ),
            out_specs=P(None, None, axis, None),
        )
        return fn(q, k_pool, v_pool, page_table, lengths)

    def _quant(q_, kp, vp, tbl, ln, ks, vs):
        return paged_chunk_attention(
            q_, kp, vp, tbl, ln, k_scale=ks, v_scale=vs
        )

    fn = shard_map_compat(
        _quant,
        mesh,
        in_specs=(
            P(None, None, axis, None), P(None, axis, None, None),
            P(None, axis, None, None), P(None, None), P(None),
            P(None, axis), P(None, axis),
        ),
        out_specs=P(None, None, axis, None),
    )
    return fn(q, k_pool, v_pool, page_table, lengths, k_scale, v_scale)


def _paged_chunk_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                        sm_scale: float, page: int, L: int,
                        quant: bool = False):
    """One (slot, logical-page) grid step of the MULTI-QUERY kernel: fold
    this page into L independent online-softmax states — one per query
    row, stacked along the scratch's leading (L*h) dim.  The L loop is a
    static unroll (L = k+1 is small), so every row's fold is the exact
    single-query recipe with its own causal limit ``base + j``.
    ``quant`` dequantizes the int8 page blocks in-kernel (per-page
    per-head scales), once per page — shared by all L rows' folds."""
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b_i = pl.program_id(0)
    p_i = pl.program_id(1)
    n_p = pl.num_programs(1)

    @pl.when(p_i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    base = len_ref[b_i]  # rows attendable by query row 0; row j sees +j

    # the page is live if ANY query row's window reaches it (row L-1 has
    # the widest window); per-row masking below zeroes the rest
    @pl.when(p_i * page < base + L - 1)
    def _compute():
        k = k_ref[0].astype(jnp.float32)               # (h, page, hd)
        v = v_ref[0].astype(jnp.float32)
        if quant:
            k = k * ks_ref[0][:, None, None]           # (h,) per-head scale
            v = v * vs_ref[0][:, None, None]
        h_ = k.shape[0]
        for j in range(L):
            lo = j * h_
            q = q_ref[0, j].astype(jnp.float32)        # (h, hd)
            scores = jnp.sum(q[:, None, :] * k, axis=-1) * sm_scale
            cols = (
                jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
                + p_i * page
            )
            scores = jnp.where(cols < base + j, scores, NEG_INF)

            m_prev = m_ref[lo:lo + h_, :1]             # (h, 1)
            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(scores - shift)                # (h, page)
            correction = jnp.where(
                jnp.isfinite(m_prev), jnp.exp(m_prev - shift), 0.0
            )
            l_ref[lo:lo + h_, :] = jnp.broadcast_to(
                correction * l_ref[lo:lo + h_, :1]
                + jnp.sum(p, axis=-1, keepdims=True),
                (h_, l_ref.shape[1]),
            )
            acc_ref[lo:lo + h_, :] = acc_ref[lo:lo + h_, :] * correction + (
                jnp.sum(p[:, :, None] * v, axis=1)
            )
            m_ref[lo:lo + h_, :] = jnp.broadcast_to(
                m_new, (h_, m_ref.shape[1])
            )

    @pl.when(p_i == n_p - 1)
    def _finalize():
        h_ = k_ref.shape[1]
        for j in range(L):
            lo = j * h_
            l = l_ref[lo:lo + h_, :1]
            denom = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, j] = (acc_ref[lo:lo + h_, :] / denom).astype(o_ref.dtype)


def paged_chunk_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Multi-query paged attention: L query tokens per slot over the paged
    KV pool — the q-length-(k+1) extension of ``paged_decode_attention``
    a speculative verify step needs (ONE program scores every position of
    every slot's draft window against the shared pool).

    q: (b, L, h, hd) — the window's rows, all already written to the pool
    at rows [lengths-1, lengths-1+L); k_pool/v_pool: (pool_pages, h,
    page_size, hd); page_table: (b, n_pages) int32; lengths: (b,) int32 —
    rows attendable by query row 0 (row j attends cols < lengths + j:
    intra-window causal).  Returns (b, L, h, hd) in q's dtype.

    Same DMA discipline as the single-query kernel: dead pages re-point
    at the last live page (pipeline elides the repeat DMA), where "live"
    is the WIDEST row's window — HBM traffic stays O(live pages), and the
    marginal cost of the extra k query rows is VPU compute only, which is
    why one verify program beats k+1 decode steps on a bandwidth-bound
    pool."""
    b, L, h, hd = q.shape
    _, hp, page, hdp = k_pool.shape
    assert (hp, hdp) == (h, hd), (k_pool.shape, q.shape)
    assert L >= 1, L
    quant = k_scale is not None
    assert quant == (v_scale is not None), "scales come in k/v pairs"
    n_pages = page_table.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def kv_map(b_i, p_i, tbl, ln):
        # live through the WIDEST window (query row L-1 attends
        # ln + L - 1 rows); dead pages alias the last live page so the
        # pipeline skips their DMA — the single-query kernel's trick
        live_pages = jnp.maximum((ln[b_i] + L - 1 + page - 1) // page, 1)
        p_eff = jnp.minimum(p_i, live_pages - 1)
        return (tbl[b_i, p_eff], 0, 0, 0)

    def scale_map(b_i, p_i, tbl, ln):
        live_pages = jnp.maximum((ln[b_i] + L - 1 + page - 1) // page, 1)
        p_eff = jnp.minimum(p_i, live_pages - 1)
        return (tbl[b_i, p_eff], 0)

    in_specs = [
        pl.BlockSpec(
            (1, L, h, hd), lambda b_i, p_i, tbl, ln: (b_i, 0, 0, 0)
        ),
        pl.BlockSpec((1, h, page, hd), kv_map),
        pl.BlockSpec((1, h, page, hd), kv_map),
    ]
    operands = [q, k_pool, v_pool]
    if quant:
        in_specs += [
            pl.BlockSpec((1, h), scale_map),
            pl.BlockSpec((1, h), scale_map),
        ]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,   # page_table, lengths
        grid=(b, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, L, h, hd), lambda b_i, p_i, tbl, ln: (b_i, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((L * h, 128), jnp.float32),  # running max per (row, head)
            pltpu.VMEM((L * h, 128), jnp.float32),  # running denominator
            pltpu.VMEM((L * h, hd), jnp.float32),   # running numerator
        ],
    )
    return pl.pallas_call(
        partial(
            _paged_chunk_kernel, sm_scale=1.0 / math.sqrt(hd), page=page,
            L=L, quant=quant,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, L, h, hd), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32), *operands)
