"""kubegpu_tpu — a TPU-native device-aware scheduling and runtime-injection framework.

A ground-up, TPU-first rebuild of the capabilities of KnifeeOneOne/KubeGPU
(a Kubernetes device-aware scheduling and CRI extension framework):

- L0 ``types``     — hierarchical grouped-resource paths, TPU mesh topology,
                     node/pod bookkeeping, annotation wire formats.
- L2 ``grpalloc``  — the allocation core: group-constraint fit, ICI-mesh
                     contiguity scoring, take/return bookkeeping. Pure logic,
                     no I/O, exhaustively unit-testable (optionally accelerated
                     by the native C++ core in ``native/``).
- L1 ``plugins``   — TPU device providers: fake (testing), libtpu/devfs/GKE
                     discovery; node advertiser; per-container Allocate.
- L4 ``scheduler`` — the scheduler-extender service: cluster cache,
                     filter/prioritize/bind HTTP endpoints, gang scheduling,
                     preemption, restart replay.
- L3 ``crishim``   — CRI proxy + env/device injection (TPU_VISIBLE_CHIPS and
                     the JAX multi-host rendezvous contract).
- L5 ``gateway``   — cluster serving front door: replica discovery from the
                     same annotations the scheduler writes, bounded fair
                     admission, load-aware routing, deadline/retry/hedge
                     failover onto the continuous-batched decode replicas.
- ``parallel``     — hands scheduled JAX workloads an ICI-contiguous sub-mesh
                     as a ``jax.sharding.Mesh``; DP/TP/SP sharding helpers.
- ``models``/``ops`` — reference JAX workloads (the payloads the samples
                     schedule): ResNet-50 data-parallel training, etc.

Design deltas vs. the reference (see SURVEY.md §7): ICI mesh coordinates are
explicit metadata scored by rectangular sub-mesh analysis (a 2D/3D torus cannot
be expressed as the reference's nested NVLink/PCIe tree); gang scheduling and
preemption are first-class; state still round-trips through Kubernetes
annotations so every component is stateless across restarts (SURVEY.md §1).

NOTE on provenance: the reference mount was empty at build time (SURVEY.md §0);
parity targets come from SURVEY.md's reconstruction and BASELINE.json.
"""

__version__ = "0.1.0"
