"""GkeTpuProvider: discover this host's TPU fragment from the real machine.

The TPU analog of the reference's NVML enumeration path (SURVEY.md §2 #6):
where NVML answered "how many GPUs, how are they linked", a GKE/GCE TPU VM
answers through (a) the TPU runtime environment variables the platform sets,
(b) /dev/accel* (or /dev/vfio/*) device nodes, and (c) optionally the native
libtpu shim (native/tpu_discovery) when present.  All inputs are injectable
so discovery is unit-testable off-TPU (fake env + fake devfs), mirroring how
the reference isolated NVML behind an interface.

Known env on GKE TPU node pools (the fiddly contract SURVEY.md §7(d) warns
about — verified against public GKE TPU docs' variable names; re-verify on a
live cluster before relying on exotic combinations):
  TPU_ACCELERATOR_TYPE  e.g. "v5litepod-16", "v4-8"
  TPU_TOPOLOGY          e.g. "4x4" (v5e), "2x2x2" (v4/v5p)
  TPU_WORKER_ID         this host's worker index within the slice, "0"..
  TPU_WORKER_HOSTNAMES  comma-separated worker hostnames (rendezvous + a
                        stable slice identity)
"""

from __future__ import annotations

import glob
import hashlib
import os
import socket
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubegpu_tpu.plugins.provider import (
    AllocateResponse,
    ENV_ACCEL_TYPE,
    ENV_TOPOLOGY,
    ENV_VISIBLE_CHIPS,
    HostFragment,
    TpuProvider,
    visible_chips_env,
)
from kubegpu_tpu.types.info import ChipRef
from kubegpu_tpu.types.topology import Chip, Coord, TpuGeneration


def parse_accelerator_type(s: str) -> Optional[Tuple[TpuGeneration, int]]:
    """"v5litepod-16" → (V5E, 16); "v4-8" → (V4, 8 TensorCores = 4 chips —
    v4/v5p accelerator types count cores, v5e/v6e count chips)."""
    s = s.strip().lower()
    if not s:
        return None
    head, _, tail = s.rpartition("-")
    try:
        n = int(tail)
    except ValueError:
        return None
    if head in ("v5litepod", "v5e"):
        return TpuGeneration.V5E, n
    if head in ("v6e", "v6litepod"):
        return TpuGeneration.V6E, n
    if head == "v4":
        return TpuGeneration.V4, n // 2
    if head in ("v5p", "v5"):
        return TpuGeneration.V5P, n // 2
    return None


def parse_topology(s: str) -> Optional[Coord]:
    """"4x4" → (4,4); "2x2x4" → (2,2,4)."""
    try:
        dims = tuple(int(p) for p in s.strip().lower().split("x"))
    except ValueError:
        return None
    return dims if dims and all(d > 0 for d in dims) else None


def _block_shape(mesh_shape: Coord, num_hosts: int, chips_local: int) -> Optional[Coord]:
    """Infer the per-host block shape: the factorization of chips_local that
    tiles mesh_shape into exactly num_hosts blocks.  Prefers blocks that are
    squarest (GKE v5e hosts own 2x2, v4 hosts own 2x2x1)."""
    from kubegpu_tpu.types.topology import factor_shapes

    candidates = []
    for shape in factor_shapes(chips_local, len(mesh_shape)):
        if any(mesh_shape[d] % shape[d] != 0 for d in range(len(mesh_shape))):
            continue
        blocks = 1
        for d in range(len(mesh_shape)):
            blocks *= mesh_shape[d] // shape[d]
        if blocks == num_hosts:
            candidates.append(shape)
    if not candidates:
        return None
    return min(candidates, key=lambda sh: (max(sh) - min(sh), sh))


class GkeTpuProvider(TpuProvider):
    def __init__(
        self,
        env: Optional[Dict[str, str]] = None,
        list_devfs: Optional[Callable[[], List[str]]] = None,
        node_name: Optional[str] = None,
        use_native: bool = True,
    ) -> None:
        self._env = dict(os.environ if env is None else env)
        # an explicitly injected devfs lister (tests, exotic hosts) takes
        # priority over the native shim; otherwise prefer native and fall
        # back to the pure-Python glob when the library isn't built
        self._list_devfs = list_devfs or self._default_devfs
        self._use_native = use_native and list_devfs is None
        self._node_name = node_name or self._env.get("NODE_NAME") or socket.gethostname()
        self._probe_cache: Tuple[float, object] = (0.0, None)
        self._probe_ttl_s = 1.0

    @staticmethod
    def _default_devfs() -> List[str]:
        return sorted(glob.glob("/dev/accel*")) or sorted(glob.glob("/dev/vfio/[0-9]*"))

    def _native_probe(self):
        """Host probe via the C++ shim (native/tpu_discovery.cpp), the
        NVML-binding analog; None when unavailable or disabled.  Briefly
        memoized: one advertise cycle (enumerate + health) and one
        CreateContainer (allocate) each cost a single devfs scan."""
        if not self._use_native:
            return None
        import time as _time

        now = _time.monotonic()
        ts, cached = self._probe_cache
        if cached is not None and now - ts < self._probe_ttl_s:
            return cached
        from kubegpu_tpu.plugins import native

        out = native.probe()
        if out is not None:
            self._probe_cache = (now, out)
        return out

    def _device_map(self) -> Dict[int, str]:
        """chip device_index -> device node path.

        /dev/accelN encodes the chip index in the path, so a missing lower
        node must NOT shift later chips (positional mapping would report the
        wrong chip dead and hand containers a neighbour's device).  Paths
        without a parseable accel index (vfio) are ranked by trailing number
        numerically (lexicographic sort puts vfio/10 before vfio/2)."""
        nat = self._native_probe()
        if nat is not None:
            return {c.index: c.path for c in nat.chips}
        paths = self._list_devfs()
        out: Dict[int, str] = {}
        unnumbered: List[Tuple[int, str]] = []
        for p in paths:
            base = p.rsplit("/", 1)[-1]
            if base.startswith("accel") and base[len("accel"):].isdigit():
                out[int(base[len("accel"):])] = p
            else:
                digits = "".join(ch for ch in base if ch.isdigit())
                unnumbered.append((int(digits) if digits else 0, p))
        if not out and unnumbered:
            for i, (_, p) in enumerate(sorted(unnumbered)):
                out[i] = p
        return out

    # -- enumeration ------------------------------------------------------
    def enumerate(self) -> Optional[HostFragment]:
        acc = parse_accelerator_type(self._env.get("TPU_ACCELERATOR_TYPE", ""))
        topo_dims = parse_topology(self._env.get("TPU_TOPOLOGY", ""))
        if acc is None or topo_dims is None:
            # CPU node: decided from env alone — no filesystem touched on
            # the advertise hot loop
            return None
        generation, total_chips = acc
        mesh_total = 1
        for d in topo_dims:
            mesh_total *= d
        if mesh_total != total_chips:
            # disagreeing platform env: trust the explicit topology
            total_chips = mesh_total

        hostnames = [
            h for h in self._env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h.strip()
        ]
        num_hosts = max(len(hostnames), 1)
        try:
            worker_id = int(self._env.get("TPU_WORKER_ID", "0"))
        except ValueError:
            worker_id = 0
        # Nominal chips per host comes from the platform (total/hosts); the
        # devfs only tells us which of those are actually present.  A host
        # with missing device nodes still advertises its full block with the
        # missing chips marked unhealthy, so the slice geometry stays intact
        # and the dead capacity falls out of the allocatable set
        # (SURVEY.md §5.3) instead of the whole host vanishing.
        if total_chips % num_hosts != 0:
            return None
        chips_local = total_chips // num_hosts
        if chips_local <= 0:
            return None
        # chips whose device node is missing are advertised unhealthy; an
        # empty devfs therefore advertises the block at zero capacity (a
        # host with no working devices must not look fully healthy)
        present = set(self._device_map())
        block = _block_shape(topo_dims, num_hosts, chips_local)
        if block is None:
            return None

        # slice identity: platform-provided name, else a stable digest of the
        # worker hostname set (same on every host of the slice)
        slice_id = self._env.get("TPU_NAME") or (
            "slice-" + hashlib.sha1(",".join(sorted(hostnames)).encode()).hexdigest()[:8]
            if hostnames
            else "slice-local"
        )

        # worker_id rasters row-major across the host-block grid
        grid = tuple(topo_dims[d] // block[d] for d in range(len(topo_dims)))
        num_blocks = 1
        for g in grid:
            num_blocks *= g
        if not (0 <= worker_id < num_blocks):
            # advertising modulo-wrapped coords would collide with another
            # host's chips and corrupt the slice view — refuse instead
            return None
        host_coord = []
        rem = worker_id
        for d in reversed(range(len(grid))):
            host_coord.append(rem % grid[d])
            rem //= grid[d]
        host_coord = tuple(reversed(host_coord))
        origin = tuple(host_coord[d] * block[d] for d in range(len(block)))

        chips: List[Chip] = []
        local = 0
        import itertools

        strides = []
        for d in range(len(topo_dims)):
            s = 1
            for d2 in range(d + 1, len(topo_dims)):
                s *= topo_dims[d2]
            strides.append(s)
        for offs in itertools.product(*(range(b) for b in block)):
            coords = tuple(origin[d] + offs[d] for d in range(len(block)))
            chip_id = sum(coords[d] * strides[d] for d in range(len(coords)))
            chips.append(
                Chip(
                    coords=coords,
                    chip_id=chip_id,
                    host_id=self._node_name,
                    device_index=local,
                    healthy=local in present,
                )
            )
            local += 1
        return HostFragment(
            node_name=self._node_name,
            slice_id=slice_id,
            generation=generation,
            mesh_shape=topo_dims,
            wrap=tuple(False for _ in topo_dims),
            chips=chips,
        )

    # -- allocate ---------------------------------------------------------
    def allocate(self, chips: Sequence[ChipRef]) -> AllocateResponse:
        env = {ENV_VISIBLE_CHIPS: visible_chips_env(chips)}
        if self._env.get("TPU_ACCELERATOR_TYPE"):
            env[ENV_ACCEL_TYPE] = self._env["TPU_ACCELERATOR_TYPE"]
        if self._env.get("TPU_TOPOLOGY"):
            env[ENV_TOPOLOGY] = self._env["TPU_TOPOLOGY"]
        dev_map = self._device_map()
        wanted = sorted({c.device_index for c in chips})
        nat = self._native_probe()
        if nat is not None:
            # the native probe adds an accessibility bit on top of presence;
            # a present-but-unopenable node is as dead as a missing one
            accessible = {c.index for c in nat.chips if c.accessible}
            dev_map = {i: p for i, p in dev_map.items() if i in accessible}
        missing = [i for i in wanted if i not in dev_map]
        if missing:
            # starting a container that believes it owns chips with no
            # device node would fail obscurely inside libtpu — fail loudly
            # at injection time instead (the CRI shim surfaces this as a
            # CreateContainer error and the pod reschedules)
            raise ValueError(
                f"allocated chip indices {missing} have no device node "
                f"(present: {sorted(dev_map)})"
            )
        devices = [dev_map[i] for i in wanted]
        return AllocateResponse(env=env, devices=devices, mounts=[])

    def healthy_device_indices(self) -> Optional[List[int]]:
        nat = self._native_probe()
        if nat is not None:
            # native adds an accessibility check on top of node presence: a
            # chip whose device node exists but cannot be opened R+W is
            # present-but-dead and must not be advertised as capacity
            return sorted(c.index for c in nat.chips if c.accessible)
        return sorted(self._device_map())
