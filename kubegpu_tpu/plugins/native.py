"""ctypes binding for the native TPU discovery shim (native/tpu_discovery.cpp).

The Python face of the framework's one native component — the TPU analog of
the reference's cgo→NVML layer (SURVEY.md §2 #7).  Loads
``libtpu_discovery.so`` and exposes a typed :func:`probe`; every consumer
must tolerate :func:`load` returning None (library not built / wrong arch)
and fall back to the pure-Python devfs scan in ``discovery.py`` — native is
an acceleration and fidelity layer, never a hard dependency.

Search order for the library: $KUBEGPU_TPU_NATIVE_LIB, the in-repo build
(native/libtpu_discovery.so), then the system loader path.
"""

from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import List, Optional

_MAX_CHIPS = 256
_PATH_MAX = 128


class _ChipNode(ctypes.Structure):
    _fields_ = [
        ("index", ctypes.c_int),
        ("path", ctypes.c_char * _PATH_MAX),
        ("accessible", ctypes.c_int),
    ]


class _HostProbe(ctypes.Structure):
    _fields_ = [
        ("chip_count", ctypes.c_int),
        ("chips", _ChipNode * _MAX_CHIPS),
        ("libtpu_present", ctypes.c_int),
        ("libtpu_has_pjrt", ctypes.c_int),
        ("libtpu_path", ctypes.c_char * _PATH_MAX),
    ]


@dataclass(frozen=True)
class ChipNode:
    index: int
    path: str
    accessible: bool


@dataclass(frozen=True)
class HostProbe:
    chips: List[ChipNode]
    libtpu_present: bool
    libtpu_has_pjrt: bool
    libtpu_path: str


_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _candidates() -> List[str]:
    out = []
    env = os.environ.get("KUBEGPU_TPU_NATIVE_LIB")
    if env:
        out.append(env)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    out.append(os.path.join(repo_root, "native", "libtpu_discovery.so"))
    out.append("libtpu_discovery.so")
    return out


def load() -> Optional[ctypes.CDLL]:
    """The shim library, or None when unavailable (cached either way)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        for path in _candidates():
            try:
                lib = ctypes.CDLL(path)
                lib.tpu_discovery_probe.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_int,
                    ctypes.POINTER(_HostProbe),
                ]
                lib.tpu_discovery_probe.restype = ctypes.c_int
                lib.tpu_discovery_version.restype = ctypes.c_char_p
                lib.tpu_discovery_probe_size.restype = ctypes.c_int
                if lib.tpu_discovery_probe_size() != ctypes.sizeof(_HostProbe):
                    # ABI mismatch (stale build with different MAX_CHIPS /
                    # PATH_MAX): calling probe would overrun our struct
                    continue
            except (OSError, AttributeError):
                # wrong library at this path (e.g. a foreign .so via
                # $KUBEGPU_TPU_NATIVE_LIB): keep trying the next candidate
                continue
            _lib = lib
            return _lib
        _load_failed = True
        return None


def version() -> Optional[str]:
    lib = load()
    return lib.tpu_discovery_version().decode() if lib else None


def probe(devfs_root: str = "/dev", check_libtpu: bool = False) -> Optional[HostProbe]:
    """One native probe of the host; None when the library is unavailable.

    check_libtpu dlopens libtpu.so to report its presence — expensive (it is
    a very large library), so off by default; the device-node scan is all
    the enumeration/health paths need."""
    lib = load()
    if lib is None:
        return None
    raw = _HostProbe()
    rc = lib.tpu_discovery_probe(devfs_root.encode(), 1 if check_libtpu else 0,
                                 ctypes.byref(raw))
    if rc != 0:
        return None
    chips = [
        ChipNode(
            index=raw.chips[i].index,
            path=raw.chips[i].path.decode(),
            accessible=bool(raw.chips[i].accessible),
        )
        for i in range(raw.chip_count)
    ]
    return HostProbe(
        chips=chips,
        libtpu_present=bool(raw.libtpu_present),
        libtpu_has_pjrt=bool(raw.libtpu_has_pjrt),
        libtpu_path=raw.libtpu_path.decode(),
    )
