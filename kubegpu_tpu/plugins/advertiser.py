"""Node advertiser daemon: publish this host's TPU fragment to the cluster.

Capability parity with the reference's advertise loop (SURVEY.md §2 #9,
§3.2): periodically (re-)enumerate devices, fold in a fresh health probe, and
patch the node object — topology annotation + ``google.com/tpu`` extended
resource capacity.  The scheduler watches nodes and rebuilds its cache from
exactly this annotation, so a chip that dies here falls out of the
allocatable set cluster-wide on the next cycle (SURVEY.md §5.3).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

from kubegpu_tpu.plugins.provider import TpuProvider
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.resource import RES_TPU
from kubegpu_tpu.utils.apiserver import ApiServer

log = logging.getLogger(__name__)


class Advertiser:
    def __init__(self, provider: TpuProvider, api: ApiServer, interval_s: float = 30.0) -> None:
        self.provider = provider
        self.api = api
        self.interval_s = interval_s
        self._stop = threading.Event()

    def advertise_once(self) -> Optional[str]:
        """One advertisement cycle; returns the node name patched, or None
        for a TPU-less host (clean no-op: BASELINE config 1 passthrough)."""
        frag = self.provider.enumerate()
        if frag is None:
            return None
        healthy = self.provider.healthy_device_indices()
        if healthy is not None:
            alive = set(healthy)
            frag.chips = [
                dataclasses.replace(ch, healthy=ch.healthy and ch.device_index in alive)
                for ch in frag.chips
            ]
        node = frag.to_node_info()
        n_healthy = sum(1 for ch in node.chips if ch.healthy)
        self.api.patch_node_annotations(
            node.name,
            {
                annotations.NODE_TOPOLOGY: annotations.encode_node_topology(node),
                # distinct value per cycle: lets the scheduler's failure
                # detector tell "new advertisement, chip still absent" from
                # "same stale annotation read twice" (strikes count the
                # former only)
                annotations.NODE_ADVERT_SEQ: str(time.time_ns()),
            },
        )
        self.api.patch_node_capacity(node.name, {RES_TPU: str(n_healthy)})
        log.info(
            "advertised %s: slice=%s chips=%d healthy=%d",
            node.name,
            node.slice_id,
            len(node.chips),
            n_healthy,
        )
        return node.name

    def run(self) -> None:
        """Blocking advertise loop (the DaemonSet entrypoint)."""
        while not self._stop.is_set():
            try:
                self.advertise_once()
            except Exception:  # noqa: BLE001 - daemon must survive API blips
                log.exception("advertise cycle failed; will retry")
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()


def main(argv=None) -> None:
    """DaemonSet entrypoint (deploy/advertiser-daemonset.yaml): discover
    this host's TPU fragment via GkeTpuProvider and advertise it to the real
    API server on a loop (--once for a single cycle)."""
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--interval", type=float, default=30.0)
    ap.add_argument("--once", action="store_true", help="one cycle, then exit")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)

    from kubegpu_tpu.plugins.discovery import GkeTpuProvider
    from kubegpu_tpu.utils.apiserver import KubeApiServer

    adv = Advertiser(GkeTpuProvider(), KubeApiServer(), interval_s=args.interval)
    if args.once:
        name = adv.advertise_once()
        log.info("advertised once: %s", name or "<no TPUs on this host>")
    else:
        adv.run()


if __name__ == "__main__":
    main()
