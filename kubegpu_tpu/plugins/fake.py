"""FakeTpuProvider: configurable in-memory TPU backend for tests/benchmarks.

Mirrors the reference's fake-NVML pattern (SURVEY.md §4): a full v5e/v4 slice
is fabricated in memory; each FakeTpuProvider instance impersonates ONE host
of it.  Supports failure injection (kill/revive chips at runtime) so cache
refresh and health-driven reallocation are testable.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubegpu_tpu.plugins.provider import (
    AllocateResponse,
    ENV_ACCEL_TYPE,
    ENV_TOPOLOGY,
    ENV_VISIBLE_CHIPS,
    HostFragment,
    TpuProvider,
    visible_chips_env,
)
from kubegpu_tpu.types.info import ChipRef
from kubegpu_tpu.types.topology import Coord, SliceTopology, TpuGeneration


class FakeSlice:
    """A fabricated slice shared by the FakeTpuProviders of its hosts."""

    def __init__(
        self,
        slice_id: str = "fake-slice",
        generation: TpuGeneration = TpuGeneration.V5E,
        mesh_shape: Coord = (4, 4),
        host_block: Coord = (2, 2),
        wrap: Optional[Tuple[bool, ...]] = None,
    ) -> None:
        self.topology = SliceTopology.build(
            slice_id, generation, mesh_shape, host_block=host_block, wrap=wrap
        )
        self.dead: Set[Coord] = set()

    def kill_chip(self, coords: Coord) -> None:
        if coords not in self.topology.chips:
            raise KeyError(f"no chip at {coords}")
        self.dead.add(coords)

    def revive_chip(self, coords: Coord) -> None:
        self.dead.discard(coords)

    def hosts(self) -> List[str]:
        return self.topology.hosts()

    def provider_for(self, host: str) -> "FakeTpuProvider":
        return FakeTpuProvider(self, host)

    def providers(self) -> Dict[str, "FakeTpuProvider"]:
        return {h: self.provider_for(h) for h in self.hosts()}


class FakeTpuProvider(TpuProvider):
    def __init__(self, fake_slice: FakeSlice, host: str) -> None:
        self._slice = fake_slice
        self._host = host

    def enumerate(self) -> Optional[HostFragment]:
        topo = self._slice.topology
        chips = []
        for ch in topo.host_chips(self._host):
            chips.append(
                dataclasses.replace(
                    ch, healthy=ch.healthy and ch.coords not in self._slice.dead
                )
            )
        if not chips:
            return None
        return HostFragment(
            node_name=self._host,
            slice_id=topo.slice_id,
            generation=topo.generation,
            mesh_shape=topo.mesh_shape,
            wrap=topo.wrap,
            chips=chips,
        )

    def allocate(self, chips: Sequence[ChipRef]) -> AllocateResponse:
        topo = self._slice.topology
        mesh = "x".join(str(d) for d in topo.mesh_shape)
        # v4/v5p accelerator types count TensorCores (2 per chip); v5e/v6e
        # count chips — keep the fake's env round-trippable through
        # discovery.parse_accelerator_type
        cores_per_chip = 2 if topo.generation in (TpuGeneration.V4, TpuGeneration.V5P) else 1
        return AllocateResponse(
            env={
                ENV_VISIBLE_CHIPS: visible_chips_env(chips),
                ENV_ACCEL_TYPE: f"{topo.generation.value}-{topo.num_chips * cores_per_chip}",
                ENV_TOPOLOGY: mesh,
            },
            devices=[f"/dev/accel{c.device_index}" for c in sorted(chips, key=lambda r: r.device_index)],
            mounts=[],
        )

    def healthy_device_indices(self) -> Optional[List[int]]:
        topo = self._slice.topology
        return [
            ch.device_index
            for ch in topo.host_chips(self._host)
            if ch.healthy and ch.coords not in self._slice.dead
        ]
