"""L1 device backends (SURVEY.md §2 #5-#7, #9): TPU providers, advertiser."""

from kubegpu_tpu.plugins.provider import (
    AllocateResponse,
    ENV_ACCEL_TYPE,
    ENV_TOPOLOGY,
    ENV_VISIBLE_CHIPS,
    HostFragment,
    TpuProvider,
    visible_chips_env,
)
from kubegpu_tpu.plugins.fake import FakeSlice, FakeTpuProvider
from kubegpu_tpu.plugins.discovery import GkeTpuProvider
from kubegpu_tpu.plugins.advertiser import Advertiser
from kubegpu_tpu.plugins.deviceplugin import DevicePluginServer

__all__ = [
    "DevicePluginServer",
    "AllocateResponse",
    "ENV_ACCEL_TYPE",
    "ENV_TOPOLOGY",
    "ENV_VISIBLE_CHIPS",
    "HostFragment",
    "TpuProvider",
    "visible_chips_env",
    "FakeSlice",
    "FakeTpuProvider",
    "GkeTpuProvider",
    "Advertiser",
]
