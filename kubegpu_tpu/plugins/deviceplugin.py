"""Kubelet Device Plugin API (v1beta1): the modern advertisement path.

The reference advertised capacity by patching node annotations/status from
its own daemon (SURVEY.md §2 #9) because the device-plugin framework did not
exist yet.  Modern kubelets expect extended resources like ``google.com/tpu``
to come from a device plugin over gRPC: the plugin serves on a unix socket
under ``/var/lib/kubelet/device-plugins/``, registers itself with kubelet's
``kubelet.sock``, streams its device inventory (``ListAndWatch``), and
answers ``Allocate`` at container-admission time.  This module provides that
surface ON TOP of the same ``TpuProvider`` backend the advertiser and CRI
shim use — both paths stay available:

- annotation path (advertiser + extender + CRI shim): topology-aware
  placement, gangs, multislice — the framework's full capability;
- device-plugin path (this module): plain kubelet-managed ``google.com/tpu``
  counts for clusters that run without the extender, with
  ``GetPreferredAllocation`` answering kubelet's choice of device IDs with
  the most ICI-contiguous subset (the same scorer grpalloc uses), so even
  extender-less allocation lands on good topology.

Wire format: the same schema-free protowire codec the CRI proxy uses
(utils/protowire.py) — no vendored protos.  Field numbers follow the public
``k8s.io/kubelet/pkg/apis/deviceplugin/v1beta1/api.proto``.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Iterable, List, Optional, Sequence

import grpc

from kubegpu_tpu.grpalloc.scoring import placement_score
from kubegpu_tpu.plugins.provider import HostFragment, TpuProvider
from kubegpu_tpu.types.info import ChipRef
from kubegpu_tpu.types.resource import RES_TPU
from kubegpu_tpu.utils import protowire as pw

log = logging.getLogger(__name__)

API_VERSION = "v1beta1"
KUBELET_SOCKET = "kubelet.sock"
DEFAULT_SOCKET_DIR = "/var/lib/kubelet/device-plugins"
DEFAULT_ENDPOINT = "kubegpu-tpu.sock"

SVC_REGISTRATION = "/v1beta1.Registration/Register"
SVC_OPTIONS = "/v1beta1.DevicePlugin/GetDevicePluginOptions"
SVC_LIST_AND_WATCH = "/v1beta1.DevicePlugin/ListAndWatch"
SVC_PREFERRED = "/v1beta1.DevicePlugin/GetPreferredAllocation"
SVC_ALLOCATE = "/v1beta1.DevicePlugin/Allocate"
SVC_PRESTART = "/v1beta1.DevicePlugin/PreStartContainer"

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

_IDENT = lambda b: b  # noqa: E731 - bytes in, bytes out


# ---------------------------------------------------------------------------
# Message builders/parsers (field numbers from deviceplugin v1beta1 api.proto)
# ---------------------------------------------------------------------------

def encode_register_request(endpoint: str, resource_name: str) -> bytes:
    """RegisterRequest{version=1, endpoint=2, resource_name=3, options=4}."""
    return (
        pw.encode_string_field(1, API_VERSION)
        + pw.encode_string_field(2, endpoint)
        + pw.encode_string_field(3, resource_name)
        + pw.encode_len_field(4, encode_options())
    )


def encode_options() -> bytes:
    """DevicePluginOptions{pre_start_required=1,
    get_preferred_allocation_available=2}."""
    return (
        # pre_start_required=false is the proto default (omitted)
        pw.encode_varint((2 << 3) | 0) + pw.encode_varint(1)
    )


def encode_device(device_id: str, healthy: bool) -> bytes:
    """Device{ID=1, health=2}."""
    return pw.encode_string_field(1, device_id) + pw.encode_string_field(
        2, HEALTHY if healthy else UNHEALTHY
    )


def encode_list_and_watch_response(devices: List[bytes]) -> bytes:
    out = bytearray()
    for d in devices:
        out += pw.encode_len_field(1, d)
    return bytes(out)


def decode_devices(payload: bytes) -> Dict[str, str]:
    """ListAndWatchResponse → {device_id: health}."""
    out: Dict[str, str] = {}
    for d in pw.get_all(payload, 1):
        d = bytes(d)
        did = pw.get_field(d, 1)
        health = pw.get_field(d, 2)
        out[bytes(did).decode() if did else ""] = (
            bytes(health).decode() if health else ""
        )
    return out


def decode_id_list_requests(payload: bytes, ids_field: int = 1) -> List[List[str]]:
    """AllocateRequest/PreStartContainerRequest-style: repeated container
    requests (field 1), each with repeated string device IDs."""
    out: List[List[str]] = []
    for creq in pw.get_all(payload, 1):
        out.append([bytes(i).decode() for i in pw.get_all(bytes(creq), ids_field)])
    return out


def decode_preferred_requests(payload: bytes) -> List[dict]:
    """PreferredAllocationRequest{container_requests=1} with
    ContainerPreferredAllocationRequest{available_deviceIDs=1,
    must_include_deviceIDs=2, allocation_size=3}."""
    out = []
    for creq in pw.get_all(payload, 1):
        creq = bytes(creq)
        size = pw.get_field(creq, 3)
        out.append(
            {
                "available": [bytes(i).decode() for i in pw.get_all(creq, 1)],
                "must_include": [bytes(i).decode() for i in pw.get_all(creq, 2)],
                "size": int(size) if isinstance(size, int) else 0,
            }
        )
    return out


def encode_container_allocate_response(
    env: Dict[str, str],
    devices: Sequence[str],
    mounts: Sequence[tuple],
) -> bytes:
    """ContainerAllocateResponse{envs=1 map, mounts=2, devices=3}."""
    out = bytearray()
    for k, v in sorted(env.items()):
        out += pw.encode_len_field(1, pw.encode_key_value(k, v))
    for host, ctr in mounts:
        # Mount{container_path=1, host_path=2, read_only=3}
        m = pw.encode_string_field(1, ctr) + pw.encode_string_field(2, host)
        m += pw.encode_varint((3 << 3) | 0) + pw.encode_varint(1)
        out += pw.encode_len_field(2, m)
    for path in devices:
        # DeviceSpec{container_path=1, host_path=2, permissions=3}
        d = (
            pw.encode_string_field(1, path)
            + pw.encode_string_field(2, path)
            + pw.encode_string_field(3, "rwm")
        )
        out += pw.encode_len_field(3, d)
    return bytes(out)


# ---------------------------------------------------------------------------
# The plugin server
# ---------------------------------------------------------------------------

class DevicePluginServer:
    """Serves the DevicePlugin service for one host's TpuProvider and
    registers it with kubelet.  ``device_id`` ↔ chip mapping is the host-local
    device index (stable across restarts — it is the /dev/accel ordinal)."""

    def __init__(
        self,
        provider: TpuProvider,
        socket_dir: str = DEFAULT_SOCKET_DIR,
        endpoint: str = DEFAULT_ENDPOINT,
        resource_name: str = RES_TPU,
        poll_interval_s: float = 5.0,
    ) -> None:
        self.provider = provider
        self.socket_dir = socket_dir
        self.endpoint = endpoint
        self.resource_name = resource_name
        self.poll_interval_s = poll_interval_s
        self._server: Optional[grpc.Server] = None
        self._stop = threading.Event()

    # -- lifecycle --------------------------------------------------------
    @property
    def socket_path(self) -> str:
        return os.path.join(self.socket_dir, self.endpoint)

    def start(self) -> None:
        from concurrent import futures

        if self._server is not None:
            # re-serve (kubelet restart churn): a started grpc.Server is
            # never garbage-collected until stopped — overwriting the
            # reference would leak its poller thread + executor per restart
            self._server.stop(0.2).wait()
            self._server = None
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)  # stale socket from a previous run
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((_PluginHandler(self),))
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        log.info("device plugin serving %s on %s", self.resource_name, self.socket_path)

    def stop(self, grace: float = 1.0) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop(grace).wait()

    def register_with_kubelet(self, kubelet_socket: Optional[str] = None) -> None:
        """One unary Register call; kubelet then dials back our endpoint."""
        target = f"unix://{kubelet_socket or os.path.join(self.socket_dir, KUBELET_SOCKET)}"
        with grpc.insecure_channel(target) as channel:
            register = channel.unary_unary(
                SVC_REGISTRATION, request_serializer=_IDENT, response_deserializer=_IDENT
            )
            register(
                encode_register_request(self.endpoint, self.resource_name),
                timeout=5.0,
            )
        log.info("registered %s with kubelet at %s", self.resource_name, target)

    def serve_forever(
        self,
        stop: threading.Event,
        kubelet_socket: Optional[str] = None,
        watch_interval_s: float = 2.0,
    ) -> None:
        """The DaemonSet serve loop, re-registration churn included —
        kubelet owns the plugin contract's lifecycle and a plugin that
        only registers once silently falls out of the allocatable set on
        the first kubelet restart:

        - our own socket vanishing = kubelet restarted and wiped
          ``/var/lib/kubelet/device-plugins`` → re-serve + re-register
          (the fsnotify trigger the NVIDIA plugin watches);
        - the kubelet socket's INODE changing = kubelet restarted without
          wiping the dir (containerized kubelets recreate their socket) →
          our old registration died with the old process → re-register;
        - registration failures (kubelet briefly down mid-restart) are
          logged and retried next tick, never fatal.
        """
        kubelet_path = kubelet_socket or os.path.join(
            self.socket_dir, KUBELET_SOCKET
        )
        last_ident: Optional[tuple] = None
        registered = False
        while not stop.is_set():
            if not os.path.exists(self.socket_path):
                log.warning(
                    "plugin socket vanished (kubelet restart); re-serving"
                )
                try:
                    self.start()
                except Exception as e:  # noqa: BLE001 - bind can fail
                    # transiently while kubelet recreates the plugin dir;
                    # the same never-fatal rule as registration
                    log.warning("re-serve failed (%s); retrying", e)
                    stop.wait(watch_interval_s)
                    continue
                registered = False
            try:
                st = os.stat(kubelet_path)
                # inode + mtime: tmpfs recycles inode numbers, so a
                # recreated socket can reuse its predecessor's — the
                # creation timestamp disambiguates
                ident: Optional[tuple] = (st.st_ino, st.st_mtime_ns)
            except OSError:
                ident = None  # kubelet down/mid-restart: wait for it
                registered = False
            if ident is not None and (not registered or ident != last_ident):
                try:
                    self.register_with_kubelet(kubelet_path)
                    registered = True
                    last_ident = ident
                except Exception as e:  # noqa: BLE001 - retry next tick
                    log.warning("kubelet registration failed (%s); retrying", e)
                    registered = False
            stop.wait(watch_interval_s)

    # -- device inventory -------------------------------------------------
    def _fragment(self) -> Optional[HostFragment]:
        return self.provider.enumerate()

    def _inventory(self) -> Dict[str, bool]:
        """device_id -> healthy, folding in a fresh health probe."""
        frag = self._fragment()
        if frag is None:
            return {}
        fresh = self.provider.healthy_device_indices()
        out = {}
        for ch in frag.chips:
            healthy = ch.healthy if fresh is None else ch.device_index in fresh
            out[str(ch.device_index)] = healthy
        return out

    def list_and_watch(self, request: bytes, context) -> Iterable[bytes]:
        """Stream the inventory; re-send whenever it changes (kubelet keeps
        this stream open for the plugin's lifetime)."""
        last: Optional[Dict[str, bool]] = None
        while not self._stop.is_set() and context.is_active():
            inv = self._inventory()
            if inv != last:
                last = inv
                yield encode_list_and_watch_response(
                    [encode_device(did, ok) for did, ok in sorted(inv.items())]
                )
            # Event.wait returns early on stop — no sleep-latency on shutdown
            self._stop.wait(self.poll_interval_s)

    # -- allocation -------------------------------------------------------
    def _chips_for_ids(self, ids: Sequence[str]) -> List[ChipRef]:
        frag = self._fragment()
        if frag is None:
            raise ValueError("no TPU fragment on this host")
        by_idx = {str(ch.device_index): ch for ch in frag.chips}
        refs = []
        for did in ids:
            ch = by_idx.get(did)
            if ch is None:
                raise ValueError(f"unknown device id {did!r}")
            refs.append(
                ChipRef(
                    host=frag.node_name,
                    device_index=ch.device_index,
                    chip_id=ch.chip_id,
                    coords=ch.coords,
                )
            )
        return refs

    def allocate(self, request: bytes, context) -> bytes:
        out = bytearray()
        for ids in decode_id_list_requests(request):
            resp = self.provider.allocate(self._chips_for_ids(ids))
            out += pw.encode_len_field(
                1,
                encode_container_allocate_response(
                    resp.env, resp.devices, resp.mounts
                ),
            )
        return bytes(out)

    def preferred_allocation(self, request: bytes, context) -> bytes:
        """kubelet's "which device IDs should I pick" — answered with the
        most ICI-contiguous subset by the allocator's own scorer, so even
        extender-less clusters get topology-aware placement."""
        frag = self._fragment()
        out = bytearray()
        for creq in decode_preferred_requests(request):
            chosen = self._prefer(
                frag, creq["available"], creq["must_include"], creq["size"]
            )
            resp = bytearray()
            for did in chosen:
                resp += pw.encode_string_field(1, did)
            out += pw.encode_len_field(1, bytes(resp))
        return bytes(out)

    def _prefer(
        self,
        frag: Optional[HostFragment],
        available: List[str],
        must_include: List[str],
        size: int,
    ) -> List[str]:
        import itertools

        if size <= 0 or size > len(available):
            return sorted(available)[:max(size, 0)]
        if frag is None:
            return sorted(available)[:size]
        coords_of = {str(ch.device_index): ch.coords for ch in frag.chips}
        must = [d for d in must_include if d in coords_of]
        pool = sorted(d for d in available if d in coords_of and d not in must)
        want = size - len(must)
        if want < 0 or want > len(pool):
            return sorted(available)[:size]
        free = frozenset(coords_of[d] for d in pool + must)
        best, best_score = None, -1.0
        for combo in itertools.combinations(pool, want):
            cset = frozenset(coords_of[d] for d in combo) | frozenset(
                coords_of[d] for d in must
            )
            s = placement_score(cset, free, frag.mesh_shape, frag.wrap)
            if s > best_score:
                best, best_score = list(combo), s
        return sorted(must + (best or []))


class _PluginHandler(grpc.GenericRpcHandler):
    def __init__(self, plugin: DevicePluginServer) -> None:
        self._p = plugin

    def service(self, handler_call_details):
        method = handler_call_details.method
        p = self._p
        if method == SVC_OPTIONS:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: encode_options(),
                request_deserializer=_IDENT,
                response_serializer=_IDENT,
            )
        if method == SVC_LIST_AND_WATCH:
            return grpc.unary_stream_rpc_method_handler(
                p.list_and_watch,
                request_deserializer=_IDENT,
                response_serializer=_IDENT,
            )
        if method == SVC_ALLOCATE:
            return grpc.unary_unary_rpc_method_handler(
                p.allocate, request_deserializer=_IDENT, response_serializer=_IDENT
            )
        if method == SVC_PREFERRED:
            return grpc.unary_unary_rpc_method_handler(
                p.preferred_allocation,
                request_deserializer=_IDENT,
                response_serializer=_IDENT,
            )
        if method == SVC_PRESTART:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: b"",  # PreStartContainerResponse{}
                request_deserializer=_IDENT,
                response_serializer=_IDENT,
            )
        return None


def main(argv=None) -> None:
    """DaemonSet entrypoint: serve + register + re-register on kubelet
    restart (kubelet deletes plugin sockets when it restarts; watching our
    own socket disappear is the standard re-registration trigger)."""
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--socket-dir", default=DEFAULT_SOCKET_DIR)
    ap.add_argument("--endpoint", default=DEFAULT_ENDPOINT)
    ap.add_argument("--resource", default=RES_TPU)
    ap.add_argument("--poll-interval", type=float, default=5.0)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)

    from kubegpu_tpu.plugins.discovery import GkeTpuProvider

    plugin = DevicePluginServer(
        GkeTpuProvider(),
        socket_dir=args.socket_dir,
        endpoint=args.endpoint,
        resource_name=args.resource,
        poll_interval_s=args.poll_interval,
    )
    plugin.start()
    stop = threading.Event()
    try:
        plugin.serve_forever(stop)
    except KeyboardInterrupt:
        stop.set()
        plugin.stop()


if __name__ == "__main__":
    main()
