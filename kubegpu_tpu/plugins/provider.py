"""TpuProvider: the device-backend interface (L1).

Capability parity with the reference's Device interface + NVML isolation
(SURVEY.md §2 #6-#7): enumerate devices and topology, report health, and
answer per-container ``Allocate`` with the env/device/mount injection set.
Every hardware dependency sits behind this interface with an in-memory fake
(SURVEY.md §4: the transferable test pattern), so the whole framework runs
and tests without TPUs.

Implementations:
- ``fake.FakeTpuProvider`` — configurable mesh + failure injection.
- ``discovery.GkeTpuProvider`` — real host discovery from the GKE TPU VM
  environment (env vars + /dev/accel* device nodes + optional libtpu C shim).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubegpu_tpu.types.info import ChipRef, NodeInfo
from kubegpu_tpu.types.topology import Chip, Coord, TpuGeneration

# The device-visibility env var the CRI shim injects (BASELINE.json north
# star names TPU_VISIBLE_CHIPS); libtpu reads it to restrict which of the
# host's chips the container's process may claim.
ENV_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"
ENV_ACCEL_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TOPOLOGY = "TPU_TOPOLOGY"


@dataclass
class HostFragment:
    """What one host contributes to a slice: the TPU analog of the
    reference's per-node NVML device tree (SURVEY.md §3.2)."""

    node_name: str
    slice_id: str
    generation: TpuGeneration
    mesh_shape: Coord
    wrap: Tuple[bool, ...]
    chips: List[Chip] = field(default_factory=list)

    def to_node_info(self) -> NodeInfo:
        node = NodeInfo(
            name=self.node_name,
            slice_id=self.slice_id,
            generation=self.generation,
            mesh_shape=self.mesh_shape,
            wrap=self.wrap,
            chips=list(self.chips),
        )
        node.rebuild_capacity()
        return node


@dataclass
class AllocateResponse:
    """Injection set for one container (SURVEY.md §3.3): env vars, device
    nodes, and mounts the CRI shim must add to the container config."""

    env: Dict[str, str] = field(default_factory=dict)
    devices: List[str] = field(default_factory=list)   # host /dev paths
    mounts: List[Tuple[str, str]] = field(default_factory=list)  # (host, ctr)


class TpuProvider:
    """Device backend interface.  All methods must be side-effect free and
    callable repeatedly (the advertiser polls enumerate/health)."""

    def enumerate(self) -> Optional[HostFragment]:
        """This host's chips with global slice coordinates; None when the
        host has no TPUs (a CPU node)."""
        raise NotImplementedError

    def allocate(self, chips: Sequence[ChipRef]) -> AllocateResponse:
        """Injection set granting a container exactly these host-local
        chips."""
        raise NotImplementedError

    def healthy_device_indices(self) -> Optional[List[int]]:
        """Fresh health probe; None = provider cannot probe (assume
        enumerate()'s view)."""
        return None


def visible_chips_env(chips: Sequence[ChipRef]) -> str:
    """Canonical TPU_VISIBLE_CHIPS value: comma-joined host-local indices,
    sorted ascending (libtpu expects a stable, duplicate-free list)."""
    return ",".join(str(i) for i in sorted({c.device_index for c in chips}))
