"""Kubernetes Events: the operator-facing record of scheduling decisions.

kube-scheduler explains itself through v1 Events (`kubectl describe pod`
shows Scheduled/FailedScheduling/Preempted); this recorder gives the
extender the same voice for the decisions only IT can explain — gang
planning, chip-health evictions, stranded-gang rollbacks, preemptions.
Events are best-effort by k8s convention: emission failures are logged and
swallowed, never allowed to fail a scheduling verb.  Identical
(object, reason, message) emissions within ``dedup_s`` are suppressed —
the resync loop re-observes conditions every 30 s and must not spam one
event per tick.
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime, timezone
from typing import Dict, Optional, Tuple

log = logging.getLogger(__name__)


class EventRecorder:
    def __init__(
        self,
        api,
        component: str = "kubegpu-tpu-scheduler",
        dedup_s: float = 300.0,
    ) -> None:
        self.api = api
        self.component = component
        self.dedup_s = dedup_s
        self._lock = threading.Lock()
        self._seen: Dict[Tuple[str, str, str], float] = {}

    def pod_event(
        self,
        namespace: str,
        name: str,
        reason: str,
        message: str,
        type_: str = "Normal",
        uid: str = "",
    ) -> None:
        """Emit one v1 Event against a Pod; dedup + best-effort."""
        key = (f"{namespace}/{name}", reason, message)
        now = time.monotonic()
        with self._lock:
            last = self._seen.get(key)
            if last is not None and now - last < self.dedup_s:
                return
            self._seen[key] = now
            # bound the memory: drop entries past their dedup window
            if len(self._seen) > 4096:
                self._seen = {
                    k: v for k, v in self._seen.items()
                    if now - v < self.dedup_s
                }
        stamp = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        # name suffix = wall-clock nanoseconds (kube-scheduler's own
        # convention): unique across restarts and HA replicas, where a
        # resettable counter would collide with a live same-named event
        # and the 409 would silently swallow the new emission.  The pod-
        # name prefix is truncated so the whole event name stays within
        # the DNS-1123 subdomain limit (253) — a real API server 422s
        # over-long names and the best-effort swallow would silently drop
        # the record exactly for long-named pods.
        suffix = f".{time.time_ns():x}"
        obj = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{name[: 253 - len(suffix)]}{suffix}",
                "namespace": namespace,
            },
            "involvedObject": {
                "apiVersion": "v1",
                "kind": "Pod",
                "namespace": namespace,
                "name": name,
                "uid": uid,
            },
            "reason": reason,
            "message": message,
            "type": type_,
            "source": {"component": self.component},
            "firstTimestamp": stamp,
            "lastTimestamp": stamp,
            "count": 1,
        }
        try:
            self.api.create_event(obj)
        except NotImplementedError:
            pass  # API fake without an events surface: stay silent
        except Exception:  # noqa: BLE001 - events are best-effort
            log.debug("event emission failed for %s/%s %s", namespace, name, reason)
