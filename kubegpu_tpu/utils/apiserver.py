"""API-server client abstraction: the framework's only stateful boundary.

The reference talks to the Kubernetes API server via client-go informers and
writes (SURVEY.md §3.1/§3.2); every component is stateless because node/pod
annotations ARE the durable state.  Same split here: an ``ApiServer``
interface small enough to fake in-memory (the test strategy SURVEY.md §4
calls the transferable pattern: every cluster dependency behind an interface
with an in-memory fake), plus a thin real client for in-cluster use.

The in-memory fake is also the engine of the simulated e2e benchmark.
"""

from __future__ import annotations

import copy
import http.client
import json
import logging
import os
import socket
import ssl
import threading
import urllib.request
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)


def _response_socket(resp) -> Optional[socket.socket]:
    """The underlying socket of an http.client.HTTPResponse, or None.

    The chain (resp.fp buffered reader → .raw SocketIO → ._sock) is a
    CPython implementation detail; resolved defensively via getattr and
    type-checked so an interpreter upgrade that breaks it returns None
    instead of raising.  test_apiserver pins this helper against a LIVE
    response so a broken chain fails the suite loudly — close_watches'
    prompt-shutdown guarantee (shutdown(SHUT_RDWR) is what wakes a reader
    blocked in recv; plain close() does not) must never silently degrade."""
    raw = getattr(getattr(resp, "fp", None), "raw", None)
    if raw is None and isinstance(getattr(resp, "fp", None), socket.SocketIO):
        raw = resp.fp  # unbuffered response (rare, but cheap to cover)
    if not isinstance(raw, socket.SocketIO):
        return None
    sock = getattr(raw, "_sock", None)
    return sock if isinstance(sock, (socket.socket, ssl.SSLSocket)) else None


class Conflict(Exception):
    """Optimistic-concurrency conflict (e.g. binding an already-bound pod)."""


class NotFound(Exception):
    pass


class ApiServer:
    """Minimal surface the framework needs; see InMemoryApiServer for the
    reference semantics."""

    # nodes
    def list_nodes(self) -> List[dict]:
        raise NotImplementedError

    def get_node(self, name: str) -> dict:
        raise NotImplementedError

    def patch_node_annotations(self, name: str, ann: Dict[str, str]) -> None:
        raise NotImplementedError

    def patch_node_capacity(self, name: str, capacity: Dict[str, str]) -> None:
        raise NotImplementedError

    # pods
    def list_pods(self, namespace: Optional[str] = None) -> List[dict]:
        raise NotImplementedError

    def get_pod(self, namespace: str, name: str) -> dict:
        raise NotImplementedError

    def create_pod(self, obj: dict) -> dict:
        raise NotImplementedError

    def delete_pod(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    def patch_pod_annotations(self, namespace: str, name: str, ann: Dict[str, str]) -> None:
        raise NotImplementedError

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        raise NotImplementedError

    # v1 Events (operator-facing decision records; best-effort)
    def create_event(self, obj: dict) -> None:
        raise NotImplementedError

    # coordination.k8s.io/v1 Leases (leader election).  update_lease is a
    # full PUT carrying the read object's resourceVersion: the API server's
    # optimistic concurrency turns it into compare-and-swap (Conflict on a
    # stale version) — the primitive leader election is built on.
    def get_lease(self, namespace: str, name: str) -> dict:
        raise NotImplementedError

    def create_lease(self, obj: dict) -> dict:
        raise NotImplementedError

    def update_lease(self, namespace: str, name: str, obj: dict) -> dict:
        raise NotImplementedError

    # watches
    def watch_nodes(self, handler: Callable[[str, dict], None],
                    stop, timeout_s: int = 30) -> None:
        """Block delivering node events — handler("node-updated"|"node-deleted",
        obj) — until `stop` (a threading.Event) is set.  Implementations own
        their reconnect loop; callers just spawn this on a thread.  The
        event-driven half of failure detection: chip-death eviction fires
        from the advertiser's patch instead of waiting for a resync tick."""
        raise NotImplementedError

    def watch_pods(self, handler: Callable[[str, dict], None],
                   stop, timeout_s: int = 30) -> None:
        """Block delivering pod events — handler("pod-created"|"pod-updated"
        |"pod-deleted", obj) — until `stop` is set.  The second informer the
        reference ran (SURVEY.md §3.5): pod deletion is the load-bearing
        event (gang-plan invalidation without waiting for TTL/resync)."""
        raise NotImplementedError


class InMemoryApiServer(ApiServer):
    """Thread-safe fake with the semantics the scheduler relies on:
    bind is exactly-once (second bind → Conflict), annotation patches merge,
    and an observer hook lets tests/benchmarks watch mutations (the moral
    equivalent of a k8s watch)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._nodes: Dict[str, dict] = {}
        self._pods: Dict[str, dict] = {}
        self._events: List[dict] = []
        self._leases: Dict[str, dict] = {}
        self._observers: List[Callable[[str, dict], None]] = []

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    def observe(self, fn: Callable[[str, dict], None]) -> None:
        """fn(event, obj) with event in {node-updated, pod-created,
        pod-updated, pod-bound, pod-deleted}."""
        with self._lock:
            self._observers.append(fn)

    def _emit(self, event: str, obj: dict) -> None:
        for fn in list(self._observers):
            fn(event, copy.deepcopy(obj))

    # -- nodes ------------------------------------------------------------
    def add_node(self, obj: dict) -> None:
        with self._lock:
            name = obj["metadata"]["name"]
            self._nodes[name] = copy.deepcopy(obj)
            self._emit("node-updated", self._nodes[name])

    def delete_node(self, name: str) -> None:
        """Node deregistration (the total-failure mode resync() sweeps for)."""
        with self._lock:
            node = self._nodes.pop(name, None)
        if node is not None:
            self._emit("node-deleted", node)

    def list_nodes(self) -> List[dict]:
        with self._lock:
            return [copy.deepcopy(n) for n in self._nodes.values()]

    def get_node(self, name: str) -> dict:
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name}")
            return copy.deepcopy(self._nodes[name])

    def patch_node_annotations(self, name: str, ann: Dict[str, str]) -> None:
        with self._lock:
            node = self._nodes.setdefault(name, {"metadata": {"name": name}})
            meta = node.setdefault("metadata", {})
            meta.setdefault("annotations", {}).update(ann)
            self._emit("node-updated", node)

    def patch_node_capacity(self, name: str, capacity: Dict[str, str]) -> None:
        with self._lock:
            node = self._nodes.setdefault(name, {"metadata": {"name": name}})
            status = node.setdefault("status", {})
            status.setdefault("capacity", {}).update(capacity)
            status.setdefault("allocatable", {}).update(capacity)
            self._emit("node-updated", node)

    # -- pods -------------------------------------------------------------
    def list_pods(self, namespace: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [
                copy.deepcopy(p)
                for p in self._pods.values()
                if namespace is None or p["metadata"].get("namespace", "default") == namespace
            ]

    def get_pod(self, namespace: str, name: str) -> dict:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._pods:
                raise NotFound(f"pod {k}")
            return copy.deepcopy(self._pods[k])

    def create_pod(self, obj: dict) -> dict:
        with self._lock:
            meta = obj.setdefault("metadata", {})
            meta.setdefault("namespace", "default")
            k = self._key(meta["namespace"], meta["name"])
            if k in self._pods:
                raise Conflict(f"pod {k} exists")
            self._pods[k] = copy.deepcopy(obj)
            self._emit("pod-created", self._pods[k])
            return copy.deepcopy(self._pods[k])

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            k = self._key(namespace, name)
            pod = self._pods.pop(k, None)
        if pod is not None:
            self._emit("pod-deleted", pod)

    def patch_pod_annotations(self, namespace: str, name: str, ann: Dict[str, str]) -> None:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._pods:
                raise NotFound(f"pod {k}")
            meta = self._pods[k].setdefault("metadata", {})
            meta.setdefault("annotations", {}).update(ann)
            self._emit("pod-updated", self._pods[k])

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._pods:
                raise NotFound(f"pod {k}")
            spec = self._pods[k].setdefault("spec", {})
            if spec.get("nodeName"):
                raise Conflict(f"pod {k} already bound to {spec['nodeName']}")
            spec["nodeName"] = node
            self._emit("pod-bound", self._pods[k])

    def create_event(self, obj: dict) -> None:
        with self._lock:
            self._events.append(copy.deepcopy(obj))

    # -- leases (optimistic-concurrency semantics of the real API server) --
    def get_lease(self, namespace: str, name: str) -> dict:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._leases:
                raise NotFound(f"lease {k}")
            return copy.deepcopy(self._leases[k])

    def create_lease(self, obj: dict) -> dict:
        with self._lock:
            meta = obj.setdefault("metadata", {})
            meta.setdefault("namespace", "default")
            k = self._key(meta["namespace"], meta.get("name", ""))
            if k in self._leases:
                raise Conflict(f"lease {k} exists")
            stored = copy.deepcopy(obj)
            stored["metadata"]["resourceVersion"] = "1"
            self._leases[k] = stored
            return copy.deepcopy(stored)

    def update_lease(self, namespace: str, name: str, obj: dict) -> dict:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._leases:
                raise NotFound(f"lease {k}")
            current_rv = self._leases[k]["metadata"].get("resourceVersion")
            sent_rv = (obj.get("metadata") or {}).get("resourceVersion")
            if sent_rv != current_rv:
                # the CAS the whole election rests on: a racing writer's
                # stale read loses with Conflict, exactly like a real API
                # server's optimistic concurrency
                raise Conflict(
                    f"lease {k}: resourceVersion {sent_rv} != {current_rv}"
                )
            stored = copy.deepcopy(obj)
            stored["metadata"]["resourceVersion"] = str(int(current_rv) + 1)
            self._leases[k] = stored
            return copy.deepcopy(stored)

    def list_events(self, namespace: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [
                copy.deepcopy(e)
                for e in self._events
                if namespace is None
                or e.get("metadata", {}).get("namespace") == namespace
            ]

    def watch_nodes(self, handler: Callable[[str, dict], None],
                    stop, timeout_s: int = 30) -> None:
        """Observer-backed watch with the same contract as the real client:
        events queue up under mutation and drain on this thread."""
        self._drain_events(
            handler, stop,
            {"node-updated": "node-updated", "node-deleted": "node-deleted"},
        )

    def watch_pods(self, handler: Callable[[str, dict], None],
                   stop, timeout_s: int = 30) -> None:
        self._drain_events(
            handler, stop,
            # pod-bound is a spec mutation: the wire client would see it as
            # MODIFIED, so deliver it as an update
            {"pod-created": "pod-created", "pod-updated": "pod-updated",
             "pod-bound": "pod-updated", "pod-deleted": "pod-deleted"},
        )

    def _drain_events(self, handler, stop, event_map: Dict[str, str]) -> None:
        import queue

        q: "queue.Queue" = queue.Queue()

        def obs(event: str, obj: dict) -> None:
            if event in event_map:
                q.put((event_map[event], obj))

        self.observe(obs)
        try:
            while not stop.is_set():
                try:
                    event, obj = q.get(timeout=0.1)
                except queue.Empty:
                    continue
                handler(event, obj)
        finally:
            with self._lock:
                self._observers.remove(obs)


class KubeApiServer(ApiServer):
    """Thin in-cluster REST client (service-account token + CA bundle).

    Capability parity with the reference's client-go usage (SURVEY.md §2 #4);
    kept deliberately minimal — JSON over HTTPS with merge-patches, the
    pods/binding subresource, and the ?watch=true long-poll stream.
    Wire-tested against a local stub HTTPS API server
    (tests/test_apiserver.py): auth/CA paths, body shapes, error mapping,
    and the watch stream."""

    TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

    def __init__(self, base_url: Optional[str] = None) -> None:
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base = base_url or f"https://{host}:{port}"
        self._ctx = ssl.create_default_context(
            cafile=self.CA if os.path.exists(self.CA) else None
        )
        # live watch streams, so close_watches() can unblock their readers
        self._watch_lock = threading.Lock()
        self._watch_conns: set = set()

    def _token(self) -> str:
        try:
            with open(self.TOKEN) as f:
                return f.read().strip()
        except OSError:
            return ""

    def _req(self, method: str, path: str, body: Optional[dict] = None,
             content_type: str = "application/json") -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base + path, data=data, method=method)
        req.add_header("Authorization", f"Bearer {self._token()}")
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req, context=self._ctx, timeout=30) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:  # pragma: no cover - needs cluster
            if e.code == 404:
                raise NotFound(path)
            if e.code == 409:
                raise Conflict(path)
            raise

    def list_nodes(self) -> List[dict]:
        return self._req("GET", "/api/v1/nodes").get("items", [])

    def get_node(self, name: str) -> dict:
        return self._req("GET", f"/api/v1/nodes/{name}")

    def patch_node_annotations(self, name: str, ann: Dict[str, str]) -> None:
        self._req(
            "PATCH",
            f"/api/v1/nodes/{name}",
            {"metadata": {"annotations": ann}},
            content_type="application/merge-patch+json",
        )

    def patch_node_capacity(self, name: str, capacity: Dict[str, str]) -> None:
        self._req(
            "PATCH",
            f"/api/v1/nodes/{name}/status",
            {"status": {"capacity": capacity, "allocatable": capacity}},
            content_type="application/merge-patch+json",
        )

    def list_pods(self, namespace: Optional[str] = None) -> List[dict]:
        path = f"/api/v1/namespaces/{namespace}/pods" if namespace else "/api/v1/pods"
        return self._req("GET", path).get("items", [])

    def get_pod(self, namespace: str, name: str) -> dict:
        return self._req("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def create_pod(self, obj: dict) -> dict:
        ns = obj.get("metadata", {}).get("namespace", "default")
        return self._req("POST", f"/api/v1/namespaces/{ns}/pods", obj)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._req("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def patch_pod_annotations(self, namespace: str, name: str, ann: Dict[str, str]) -> None:
        self._req(
            "PATCH",
            f"/api/v1/namespaces/{namespace}/pods/{name}",
            {"metadata": {"annotations": ann}},
            content_type="application/merge-patch+json",
        )

    def create_event(self, obj: dict) -> None:
        ns = obj.get("metadata", {}).get("namespace", "default")
        self._req("POST", f"/api/v1/namespaces/{ns}/events", obj)

    LEASES = "/apis/coordination.k8s.io/v1/namespaces"

    def get_lease(self, namespace: str, name: str) -> dict:
        return self._req("GET", f"{self.LEASES}/{namespace}/leases/{name}")

    def create_lease(self, obj: dict) -> dict:
        ns = obj.get("metadata", {}).get("namespace", "default")
        return self._req("POST", f"{self.LEASES}/{ns}/leases", obj)

    def update_lease(self, namespace: str, name: str, obj: dict) -> dict:
        # full PUT with the read resourceVersion: the API server CAS-es it
        return self._req(
            "PUT", f"{self.LEASES}/{namespace}/leases/{name}", obj
        )

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        self._req(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
            {
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {"name": name, "namespace": namespace},
                "target": {"apiVersion": "v1", "kind": "Node", "name": node},
            },
        )

    def watch_nodes(self, handler: Callable[[str, dict], None],
                    stop, timeout_s: int = 30) -> None:
        """k8s watch stream: GET /api/v1/nodes?watch=true, one JSON event
        per line ({"type": ADDED|MODIFIED|DELETED, "object": node}).  The
        server closes the stream after timeoutSeconds (normal k8s watch
        behavior) and this loop re-establishes it — from the last seen
        resourceVersion, so reconnects deliver deltas instead of replaying
        the whole node set — until `stop` is set.  Errors back off
        exponentially with a warning: a permanently-failing watch (e.g.
        RBAC missing the watch verb) must be visible to the operator, who
        is otherwise silently down to the slow resync path."""
        self._watch(
            "/api/v1/nodes", handler, stop, timeout_s,
            # ADDED folds into node-updated: a new node is just a cache
            # update, and reconnect-replays must be idempotent anyway
            {"ADDED": "node-updated", "MODIFIED": "node-updated",
             "DELETED": "node-deleted"},
        )

    def watch_pods(self, handler: Callable[[str, dict], None],
                   stop, timeout_s: int = 30) -> None:
        """Same stream discipline over /api/v1/pods — the second informer
        the reference ran (SURVEY.md §3.5 "client-go informers: nodes,
        pods").  DELETED is the load-bearing event: a deleted gang member
        invalidates its plan immediately instead of waiting for plan-TTL
        expiry or the next resync LIST."""
        self._watch(
            "/api/v1/pods", handler, stop, timeout_s,
            {"ADDED": "pod-created", "MODIFIED": "pod-updated",
             "DELETED": "pod-deleted"},
        )

    def close_watches(self) -> None:
        """Prompt shutdown: shut down any live watch streams' sockets so
        their reader threads unblock immediately instead of waiting out a
        quiet window (up to timeout_s+5 s).  ``shutdown(SHUT_RDWR)`` is
        the load-bearing call — closing a file descriptor does NOT wake a
        thread already blocked in recv().  Callers set `stop` FIRST; the
        watch loop treats the resulting read error as a normal stream
        drop and then observes stop."""
        with self._watch_lock:
            conns = list(self._watch_conns)
        for resp in conns:
            try:
                sock = _response_socket(resp)
                if sock is not None:
                    sock.shutdown(socket.SHUT_RDWR)
            except Exception:  # noqa: BLE001 - already closed/racing
                pass
            try:
                resp.close()
            except Exception:  # noqa: BLE001
                pass

    def _watch(self, base_path: str, handler: Callable[[str, dict], None],
               stop, timeout_s: int, event_map: Dict[str, str]) -> None:
        rv: Optional[str] = None
        backoff = 1.0
        while not stop.is_set():
            path = f"{base_path}?watch=true&timeoutSeconds={timeout_s}"
            if rv:
                path += f"&resourceVersion={rv}"
            req = urllib.request.Request(self.base + path)
            req.add_header("Authorization", f"Bearer {self._token()}")
            req.add_header("Accept", "application/json")
            try:
                resp = urllib.request.urlopen(
                    req, context=self._ctx, timeout=timeout_s + 5
                )
                try:
                    with self._watch_lock:
                        self._watch_conns.add(resp)
                    backoff = 1.0  # stream established
                    lines = iter(resp)
                    while True:
                        if stop.is_set():
                            return
                        try:
                            line = next(lines)
                        except StopIteration:
                            break
                        except AttributeError as e:
                            # close_watches() racing the read one
                            # instruction past the socket errors:
                            # resp.close() nulls resp.fp mid-read and
                            # http.client._close_conn dereferences it
                            # ("'NoneType' object has no attribute
                            # 'close'" — VERDICT r4 weak 2).  Scoped to
                            # the READ only: an AttributeError raised by
                            # an event handler below is a real bug and
                            # must propagate, not be retried as a
                            # stream drop.
                            raise http.client.HTTPException(
                                f"watch stream closed mid-read: {e}"
                            ) from e
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            evt = json.loads(line)
                        except json.JSONDecodeError:
                            continue  # partial line at stream close
                        etype = evt.get("type", "")
                        obj = evt.get("object") or {}
                        new_rv = (obj.get("metadata") or {}).get(
                            "resourceVersion"
                        )
                        if new_rv:
                            rv = new_rv
                        if etype in event_map:
                            handler(event_map[etype], obj)
                        elif etype == "ERROR":
                            # 410 Gone as a stream event: the
                            # resourceVersion is too old; restart fresh
                            rv = None
                finally:
                    with self._watch_lock:
                        self._watch_conns.discard(resp)
                    try:
                        resp.close()
                    except Exception:  # noqa: BLE001 - racing close_watches
                        pass
            except urllib.error.HTTPError as e:
                if e.code == 410:  # Gone: stale resourceVersion
                    rv = None
                    continue
                log.warning("%s watch request failed (HTTP %s); retrying "
                            "in %.0fs", base_path, e.code, backoff)
                if stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)
            except (OSError, urllib.error.URLError, ValueError,
                    http.client.HTTPException) as e:
                # ValueError/HTTPException: a close_watches() racing the
                # read surfaces as "I/O operation on closed file" — a
                # normal stream drop, not a crash.  (The same race one
                # instruction later surfaces as AttributeError inside the
                # read; the next() wrapper above converts exactly that to
                # HTTPException so a handler's own AttributeError still
                # propagates as the bug it is.)
                if stop.is_set():
                    return  # close_watches() during shutdown
                log.warning("%s watch stream dropped (%s); retrying in "
                            "%.0fs", base_path, e, backoff)
                if stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)
