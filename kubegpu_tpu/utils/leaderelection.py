"""Lease-based leader election over ``coordination.k8s.io/v1``.

The extender's no-double-allocation invariant assumes exactly ONE process
serves verbs: the cluster cache is in-memory per process, so two replicas
each assuming the same chips would silently double-allocate (VERDICT r3
missing #1).  ``deploy/device-scheduler.yaml``'s ``replicas: 1`` was the
only guard — one typo, or the overlap window of a rolling update, away
from breaking.  This module is the k8s-native fix, mirroring client-go's
``leaderelection`` semantics (kube-scheduler/kube-controller-manager HA):

- one Lease object names the leader (``spec.holderIdentity``);
- acquire when unheld or expired — expiry judged by the holder's record
  sitting UNCHANGED for ``leaseDurationSeconds`` on the OBSERVER's own
  monotonic clock (client-go's observedRenewTime), never by comparing the
  lease's wall-clock stamps against local time (inter-node clock skew
  would corrupt the window); renew while held;
- every write is compare-and-swap via the object's ``resourceVersion``
  (the API server's optimistic concurrency) — two racing acquirers cannot
  both win;
- on clean shutdown the holder releases (clears holderIdentity), so a
  rolling update hands off immediately instead of waiting out the lease.

``is_leader()`` is deliberately conservative: leadership is claimed only
within ``lease_duration_s`` of the LAST SUCCESSFUL renew, stamped from
BEFORE the renew write was issued (monotonic clock).  On API-server
trouble the leader therefore STOPS CLAIMING leadership no later than the
moment a standby could first legitimately acquire — the two can overlap
in "nobody serves" (safe, kube-scheduler retries) but never both pass
the verb gate.  A Lease is still not a true fencing token: a durable
write ISSUED while leading can land after the window closes.  The
extender narrows that to the API round-trip (bind re-checks the gate
immediately before its annotation write — ``Scheduler.serving_gate``)
and the conflict sweep's durable double-annotation eviction resolves
any residue.

SURVEY.md §1's data-flow contract (all durable state in the API server)
is what makes warm standby cheap: the loser keeps its cache fresh via
resync but serves nothing and evicts nothing; on acquiring it replays
annotations and is immediately correct.
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime, timezone
from typing import Callable, Optional

from kubegpu_tpu.utils.apiserver import ApiServer, Conflict, NotFound

log = logging.getLogger(__name__)

_TS = "%Y-%m-%dT%H:%M:%S.%fZ"  # k8s MicroTime


def _now_utc() -> datetime:
    return datetime.now(timezone.utc)


def _fmt(ts: datetime) -> str:
    return ts.strftime(_TS)


class LeaderElector:
    def __init__(
        self,
        api: ApiServer,
        identity: str,
        namespace: str = "kube-system",
        name: str = "kubegpu-tpu-scheduler",
        lease_duration_s: float = 15.0,
        renew_period_s: float = 5.0,
        retry_period_s: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        assert renew_period_s < lease_duration_s, (
            "renew must fit inside the lease or leadership flaps"
        )
        self.api = api
        self.identity = identity
        self.namespace = namespace
        self.name = name
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.retry_period_s = retry_period_s
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._lock = threading.Lock()
        self._held = False
        # monotonic stamp taken BEFORE the renew write was issued: the
        # leadership window must start when the write left, not when it
        # returned — measuring after the PUT would let a slow round-trip
        # extend our claim past the instant a standby may legitimately
        # acquire (their clock starts from the lease content we wrote)
        self._last_renew = 0.0
        # another holder's lease record as last observed + when (monotonic,
        # OUR clock): expiry is judged by "unchanged for leaseDuration on
        # my own clock" — client-go's observedRenewTime semantics — never
        # by comparing the lease's wall-clock timestamps against ours,
        # which inter-node clock skew would corrupt
        self._observed: Optional[tuple] = None
        self._observed_at = 0.0

    # -- state -------------------------------------------------------------
    def is_leader(self) -> bool:
        """Conservative leadership: held AND renewed within the lease
        window.  A leader that cannot reach the API server stops claiming
        leadership no later than a standby could first acquire."""
        with self._lock:
            return (
                self._held
                and time.monotonic() - self._last_renew < self.lease_duration_s
            )

    def _set_held(self, held: bool, stamp: Optional[float] = None) -> None:
        with self._lock:
            if held:
                self._held = True
                self._last_renew = (
                    stamp if stamp is not None else time.monotonic()
                )
            else:
                self._held = False

    # -- lease mechanics ---------------------------------------------------
    def _lease_spec(self, transitions: int, acquire_time: Optional[str]) -> dict:
        now = _fmt(_now_utc())
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration_s),
            "acquireTime": acquire_time or now,
            "renewTime": now,
            "leaseTransitions": transitions,
        }

    def _expired(self, spec: dict) -> bool:
        """Another holder's lease is expired when its record has sat
        UNCHANGED for leaseDurationSeconds on OUR monotonic clock
        (client-go's observedRenewTime).  The lease's own wall-clock
        timestamps are never compared against our clock — skew between
        nodes would otherwise shrink (unsafe) or stretch the window."""
        if not spec.get("renewTime") and not spec.get("acquireTime"):
            return True  # never-renewed husk (e.g. released pre-timestamps)
        rec = (
            spec.get("holderIdentity"),
            spec.get("renewTime"),
            spec.get("acquireTime"),
        )
        now = time.monotonic()
        with self._lock:
            if rec != self._observed:
                # the record moved: its holder is alive — restart our timer
                self._observed = rec
                self._observed_at = now
                return False
            dur = float(
                spec.get("leaseDurationSeconds") or self.lease_duration_s
            )
            return now - self._observed_at > dur

    def try_acquire_or_renew(self) -> str:
        """One acquire/renew attempt.  Returns ``"ok"`` (we hold the lease),
        ``"lost"`` (someone else positively holds it / won the CAS race), or
        ``"error"`` (API trouble — unknown).  Every write goes through
        create (POST, conflicts if it exists) or update-with-
        resourceVersion (CAS) — losing a race is a clean "lost", never a
        shared lease.  The tri-state matters for the run loop: a DEFINITE
        loss drops leadership immediately, while a transient error leaves
        the is_leader() lease-window timeout in charge (client-go's
        renewDeadline semantics) — one API blip must not flap a healthy
        leader."""
        try:
            lease = self.api.get_lease(self.namespace, self.name)
        except NotFound:
            obj = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self.name, "namespace": self.namespace},
                "spec": self._lease_spec(0, None),
            }
            try:
                self.api.create_lease(obj)
                return "ok"
            except Conflict:
                return "lost"  # lost the creation race
            except Exception as e:  # noqa: BLE001
                log.warning("lease create failed: %s", e)
                return "error"
        except Exception as e:  # noqa: BLE001
            log.warning("lease read failed: %s", e)
            return "error"

        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        if holder and holder != self.identity and not self._expired(spec):
            return "lost"  # someone else holds a live lease
        transitions = int(spec.get("leaseTransitions") or 0)
        if holder != self.identity:
            transitions += 1
        lease["spec"] = self._lease_spec(
            transitions,
            spec.get("acquireTime") if holder == self.identity else None,
        )
        try:
            self.api.update_lease(self.namespace, self.name, lease)
            return "ok"
        except (Conflict, NotFound):
            return "lost"  # a racing writer got there first
        except Exception as e:  # noqa: BLE001
            log.warning("lease update failed: %s", e)
            return "error"

    def release(self) -> None:
        """Best-effort immediate handoff on clean shutdown: clear the
        holder so a standby acquires on its next retry instead of waiting
        out the lease."""
        try:
            lease = self.api.get_lease(self.namespace, self.name)
            spec = lease.get("spec") or {}
            if spec.get("holderIdentity") != self.identity:
                return
            spec["holderIdentity"] = ""
            spec["renewTime"] = _fmt(_now_utc())
            self.api.update_lease(self.namespace, self.name, lease)
        except Exception:  # noqa: BLE001 - shutdown: nothing left to do
            pass
        self._set_held(False)

    # -- loop --------------------------------------------------------------
    def run(self, stop: threading.Event) -> None:
        """Block acquiring/renewing until ``stop``; thread target.  On exit
        a held lease is released (rolling-update handoff)."""
        while not stop.is_set():
            was = self.is_leader()
            t0 = time.monotonic()  # window starts when the write is ISSUED
            outcome = self.try_acquire_or_renew()
            if outcome == "ok":
                if not was and self.on_started_leading:
                    # promotion readiness BEFORE the verb gate opens: a
                    # fresh leader must replay API-server state (the
                    # callback is the cache refresh) before is_leader()
                    # lets the first bind through — or it binds against a
                    # cache up to a resync interval stale
                    try:
                        self.on_started_leading()
                    except Exception:  # noqa: BLE001
                        log.exception(
                            "on_started_leading failed; holding the lease "
                            "but deferring promotion to the next cycle"
                        )
                        self._set_held(False)
                        if stop.wait(self.retry_period_s):
                            break
                        continue
                self._set_held(True, stamp=t0)
            elif outcome == "lost":
                # positively observed another holder (or lost a CAS race):
                # drop NOW, don't coast on the lease window
                self._set_held(False)
            # "error": keep the claim; is_leader()'s lease-window timeout
            # retires it before a standby could legitimately acquire
            now = self.is_leader()
            if now and not was:
                log.info("acquired leadership as %s", self.identity)
            elif was and not now:
                log.warning("lost leadership as %s", self.identity)
                if self.on_stopped_leading:
                    self.on_stopped_leading()
            if stop.wait(self.renew_period_s if now else self.retry_period_s):
                break
        if self._held:
            self.release()
            if self.on_stopped_leading:
                self.on_stopped_leading()
