"""Host-side request tracing: span trees for the serving path.

Every served request's life — gateway admission wait, routing, dispatch
(and hedges/retries), then replica-side prefill-station wait, prefill
chunks, prefix-cache gather, speculative draft/verify, decode, retire —
becomes one tree of SPANS under a single trace, so "where did TTFT go"
is answerable from data instead of print statements.  Design points:

- **Lightweight and host-only.**  A span is (trace_id, span_id,
  parent_id, name, monotonic start/end, attributes).  Opening/closing a
  span is a few dict operations under one lock; no sockets, no
  serialization on the hot path.  A batcher or gateway built without a
  tracer pays nothing.
- **Bounded.**  Completed traces live in a ring (``max_traces``);
  overflow evicts oldest and counts ``evicted`` so an oracle can tell
  "all traces retained" from "sampled".  Open traces are bounded by
  ``max_open`` (a leak guard, not a working limit): past it the oldest
  open trace is force-completed with its spans marked ``abandoned``.
- **Completion is structural.**  A trace moves to the completed ring
  only when its ROOT span has ended AND no span in it remains open.  A
  hedge loser's cancel landing after the gateway already recorded the
  winner therefore still completes the trace (the root's end stamp does
  not change); a span leaked open keeps the trace in the open set where
  ``open_count``/``wait_quiescent`` expose it.
- **The tree doubles as a correctness oracle.**  ``validate_trace``
  checks single-root, zero orphans, all spans closed, start/end sanity,
  and containment (a child runs inside its parent) — with an explicit
  escape hatch: spans carrying ``overhang_ok=True`` (and their
  subtrees) may outlive their parent, which is exactly the hedge/cancel
  asynchrony of dispatch attempts.  ``serve_retire_violations`` holds
  every replica-side ``serve`` subtree to EXACTLY one ``retire`` span —
  the trace-derived re-statement of soak invariant I5's "served exactly
  once, torn down exactly once" at the batcher level.
- **JSONL dump.**  ``dump_jsonl`` writes one span per line (see
  README "Observability" for the schema); ``load_jsonl`` reads it back
  into the same span dicts ``validate_trace`` accepts, so a dumped
  trace from a production incident replays through the same oracles the
  tests use.

Cross-process propagation: in-process the SpanCtx handle IS the
context; across the wire (gateway/dataplane.py) the dispatch span's
ids travel as ``X-Trace-Id``/``X-Span-Id`` headers, the replica serves
under its OWN tracer, ships its finished span dicts back in the
stream's terminal event, and ``Tracer.graft`` renumbers them into the
gateway's tree under the dispatch span — offsetting the remote
monotonic clock so the subtree lands inside the dispatch window.  One
request, one tree, two processes.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional

SCHEMA_VERSION = 1

# containment slack: spans are stamped with separate time.monotonic()
# calls; a child opened "at the same moment" as its parent may read a
# tick earlier on coarse clocks
_EPS = 1e-6


def _span_dict(trace_id: str, span_id: int, parent_id: Optional[int],
               name: str, start: float) -> dict:
    return {
        "trace": trace_id, "span": span_id, "parent": parent_id,
        "name": name, "start": start, "end": None, "attrs": {},
    }


class SpanCtx:
    """Handle to one open span: the in-process trace context.

    Passed down the serving path (gateway request → dispatch attempt →
    batcher ``submit(trace=...)``) so replica-side spans nest under the
    gateway's tree.  All methods are idempotent-safe after the span
    ends (a late annotate/end on a closed span is a no-op — see
    ``Tracer.end_span``)."""

    __slots__ = ("tracer", "trace_id", "span_id", "start")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: int,
                 start: float) -> None:
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.start = start

    def child(self, name: str, t: Optional[float] = None,
              **attrs) -> "SpanCtx":
        return self.tracer.start_span(self, name, t=t, **attrs)

    def annotate(self, **attrs) -> None:
        self.tracer.annotate(self, **attrs)

    def end(self, t: Optional[float] = None, **attrs) -> None:
        self.tracer.end_span(self, t=t, **attrs)

    def event(self, name: str, t: Optional[float] = None, **attrs) -> None:
        """Point-in-time child span (start == end): retire markers,
        retry decisions — tree nodes, so the oracles see them."""
        t = time.monotonic() if t is None else t
        self.tracer.start_span(self, name, t=t, **attrs).end(t=t)


class Tracer:
    """Bounded in-memory span store; every method thread-safe."""

    def __init__(self, max_traces: int = 256, max_open: int = 4096) -> None:
        self._lock = threading.Lock()
        # trace_id -> {span_id: span dict}; insertion-ordered so the
        # leak guard evicts oldest-opened first
        self._open: "OrderedDict[str, Dict[int, dict]]" = OrderedDict()
        self._open_spans: Dict[str, int] = {}   # trace_id -> open span count
        self._completed: "OrderedDict[str, Dict[int, dict]]" = OrderedDict()
        self.max_traces = max_traces
        self.max_open = max_open
        self.evicted = 0          # completed traces dropped by the ring
        self.aborted = 0          # open traces force-completed (leak guard)
        self._next_span = 0
        self._next_trace = 0

    # -- span lifecycle ----------------------------------------------------
    def start_trace(self, name: str, trace_id: Optional[str] = None,
                    t: Optional[float] = None, **attrs) -> SpanCtx:
        t = time.monotonic() if t is None else t
        with self._lock:
            if trace_id is None:
                self._next_trace += 1
                trace_id = f"t{self._next_trace:08x}"
            self._next_span += 1
            sid = self._next_span
            span = _span_dict(trace_id, sid, None, name, t)
            span["attrs"].update(attrs)
            self._open[trace_id] = {sid: span}
            self._open_spans[trace_id] = 1
            while len(self._open) > self.max_open:
                victim, spans = self._open.popitem(last=False)
                self._open_spans.pop(victim, None)
                now = time.monotonic()
                for s in spans.values():
                    if s["end"] is None:
                        s["end"] = now
                        s["attrs"]["abandoned"] = True
                self.aborted += 1
                self._complete_locked(victim, spans)
        return SpanCtx(self, trace_id, sid, t)

    def start_span(self, parent: SpanCtx, name: str,
                   t: Optional[float] = None, **attrs) -> SpanCtx:
        t = time.monotonic() if t is None else t
        with self._lock:
            spans = self._open.get(parent.trace_id)
            self._next_span += 1
            sid = self._next_span
            if spans is None:
                # the trace already completed (e.g. a hedge loser's span
                # opening after the leak guard force-closed it): record
                # nothing, hand back an inert ctx — late arrivals must
                # never resurrect a completed trace
                return SpanCtx(self, parent.trace_id, -sid, t)
            span = _span_dict(parent.trace_id, sid, parent.span_id, name, t)
            span["attrs"].update(attrs)
            spans[sid] = span
            self._open_spans[parent.trace_id] += 1
        return SpanCtx(self, parent.trace_id, sid, t)

    def annotate(self, ctx: SpanCtx, **attrs) -> None:
        with self._lock:
            spans = self._open.get(ctx.trace_id)
            if spans is None:
                return
            span = spans.get(ctx.span_id)
            if span is not None:
                span["attrs"].update(attrs)

    def end_span(self, ctx: SpanCtx, t: Optional[float] = None,
                 **attrs) -> None:
        t = time.monotonic() if t is None else t
        with self._lock:
            spans = self._open.get(ctx.trace_id)
            if spans is None:
                return
            span = spans.get(ctx.span_id)
            if span is None or span["end"] is not None:
                return  # idempotent: double-end is a no-op, not a flap
            span["attrs"].update(attrs)
            span["end"] = t
            self._open_spans[ctx.trace_id] -= 1
            root = spans[min(spans)]
            if root["end"] is not None and self._open_spans[ctx.trace_id] == 0:
                del self._open[ctx.trace_id]
                del self._open_spans[ctx.trace_id]
                self._complete_locked(ctx.trace_id, spans)

    def graft(self, parent: SpanCtx, spans: Iterable[dict],
              offset: float = 0.0) -> int:
        """Stitch a FOREIGN trace's spans (a remote replica's, shipped
        back over the wire as dicts) under ``parent`` — the cross-process
        half of request tracing.  Span ids are renumbered into this
        tracer's id space, the remote root re-parents onto ``parent``,
        and ``offset`` maps the remote monotonic clock onto ours (the
        caller anchors the remote receive stamp at its own send time, so
        the subtree lands inside the parent's window).  Every grafted
        span arrives CLOSED — a remote span still open at dump time is
        force-closed at its start and marked ``remote_unclosed`` — so
        grafting never changes when the local trace completes.  Returns
        the span count grafted (0 when the parent's trace has already
        completed: a hedge loser's late stream must never resurrect a
        finished tree)."""
        spans = list(spans)
        with self._lock:
            target = self._open.get(parent.trace_id)
            if target is None or parent.span_id < 0 or not spans:
                return 0
            idmap: Dict[int, int] = {}
            for s in sorted(spans, key=lambda s: s["span"]):
                self._next_span += 1
                idmap[s["span"]] = self._next_span
            for s in sorted(spans, key=lambda s: s["span"]):
                attrs = dict(s.get("attrs") or {}, remote=True)
                end = s.get("end")
                if end is None:
                    end = s["start"]
                    attrs["remote_unclosed"] = True
                parent_id = s.get("parent")
                new = {
                    "trace": parent.trace_id,
                    "span": idmap[s["span"]],
                    # a remote orphan (its parent missing from the dump)
                    # re-parents onto the graft point too — the local
                    # tree must stay orphan-free whatever arrived
                    "parent": idmap.get(parent_id, parent.span_id)
                    if parent_id is not None else parent.span_id,
                    "name": s["name"],
                    "start": s["start"] + offset,
                    "end": end + offset,
                    "attrs": attrs,
                }
                target[new["span"]] = new
            return len(spans)

    def _complete_locked(self, trace_id: str, spans: Dict[int, dict]) -> None:
        self._completed[trace_id] = spans
        while len(self._completed) > self.max_traces:
            self._completed.popitem(last=False)
            self.evicted += 1

    # -- views -------------------------------------------------------------
    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def wait_quiescent(self, timeout: float = 5.0) -> bool:
        """True once no trace remains open — the settle the trace
        oracles need after a drain (hedge-loser cancels land async)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.open_count() == 0:
                return True
            time.sleep(0.005)
        return self.open_count() == 0

    def completed(self) -> List[List[dict]]:
        """Completed traces, oldest first, each a list of span dicts."""
        with self._lock:
            return [
                [dict(s, attrs=dict(s["attrs"])) for s in spans.values()]
                for spans in self._completed.values()
            ]

    def trace(self, trace_id: str) -> Optional[List[dict]]:
        with self._lock:
            spans = self._completed.get(trace_id) or self._open.get(trace_id)
            if spans is None:
                return None
            return [dict(s, attrs=dict(s["attrs"])) for s in spans.values()]

    def dump_traces(self, limit: Optional[int] = None) -> List[dict]:
        """JSON-able trace trees, newest first: the /debug/trace body.
        Only the newest ``limit`` traces are copied — completed traces
        are immutable, so the lock is held for a pointer-list snapshot,
        never for the deep copy (a debug read must not stall span
        recording on a big ring)."""
        with self._lock:
            snapshot = list(self._completed.values())
        snapshot.reverse()
        if limit is not None:
            snapshot = snapshot[:limit]
        return [
            span_tree(
                [dict(s, attrs=dict(s["attrs"])) for s in spans.values()]
            )
            for spans in snapshot
        ]

    def dump_jsonl(self, path) -> int:
        """One span per line (schema v1; see README).  Returns the span
        count written.  ``path`` is a filesystem path or a file-like
        object with ``write``."""
        fh = path if hasattr(path, "write") else open(path, "w")
        n = 0
        try:
            for spans in self.completed():
                for s in sorted(spans, key=lambda s: s["span"]):
                    fh.write(json.dumps(dict(s, v=SCHEMA_VERSION)) + "\n")
                    n += 1
        finally:
            if fh is not path:
                fh.close()
        return n


def load_jsonl(path) -> Dict[str, List[dict]]:
    """Inverse of ``dump_jsonl``: {trace_id: [span dicts]}."""
    fh = path if hasattr(path, "read") else open(path)
    traces: Dict[str, List[dict]] = {}
    try:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            s = json.loads(line)
            traces.setdefault(s["trace"], []).append(s)
    finally:
        if fh is not path:
            fh.close()
    return traces


# ---------------------------------------------------------------------------
# Oracles: the span tree as a correctness artifact
# ---------------------------------------------------------------------------

def span_tree(spans: Iterable[dict]) -> dict:
    """Nest spans into {.., children: [...]} under the root; orphans
    (parent missing) attach under a synthetic "__orphans__" node so a
    broken trace still renders for debugging."""
    by_id = {
        s["span"]: dict(s, attrs=dict(s["attrs"]), children=[])
        for s in spans
    }
    root = None
    orphans = []
    for s in sorted(by_id.values(), key=lambda s: s["span"]):
        if s["parent"] is None:
            root = s
        elif s["parent"] in by_id:
            by_id[s["parent"]]["children"].append(s)
        else:
            orphans.append(s)
    tree = root if root is not None else {
        "trace": next(iter(by_id.values()))["trace"] if by_id else "",
        "name": "__no_root__", "children": [],
    }
    if orphans:
        tree = dict(tree)
        tree.setdefault("children", []).append(
            {"name": "__orphans__", "children": orphans}
        )
    return tree


def validate_trace(spans: List[dict]) -> List[str]:
    """Structural problems of one trace's span list ([] = sound):
    exactly one root, zero orphans, every span closed, end >= start,
    and children contained in their parents — except subtrees under a
    span with ``overhang_ok`` (async teardown: hedge losers cancelled
    after the winner's result already closed the gateway root)."""
    problems: List[str] = []
    by_id = {s["span"]: s for s in spans}
    roots = [s for s in spans if s["parent"] is None]
    if len(roots) != 1:
        problems.append(f"{len(roots)} roots (want exactly 1)")
    for s in spans:
        label = f"span {s['span']} ({s['name']})"
        if s["parent"] is not None and s["parent"] not in by_id:
            problems.append(f"{label}: orphan (parent {s['parent']} missing)")
        if s["end"] is None:
            problems.append(f"{label}: never closed")
        elif s["end"] < s["start"] - _EPS:
            problems.append(f"{label}: ends before it starts")
        if s["attrs"].get("abandoned"):
            problems.append(f"{label}: abandoned (force-closed)")

    def overhang_exempt(s: dict) -> bool:
        seen = set()
        while s is not None:
            if s["attrs"].get("overhang_ok"):
                return True
            if s["span"] in seen:
                return True  # cycle: already reported via containment
            seen.add(s["span"])
            s = by_id.get(s["parent"]) if s["parent"] is not None else None
        return False

    for s in spans:
        if s["parent"] is None or s["parent"] not in by_id:
            continue
        p = by_id[s["parent"]]
        label = f"span {s['span']} ({s['name']})"
        if s["start"] < p["start"] - _EPS:
            problems.append(
                f"{label}: starts before its parent ({p['name']})"
            )
        if (
            s["end"] is not None and p["end"] is not None
            and s["end"] > p["end"] + _EPS
            and not overhang_exempt(s)
        ):
            problems.append(
                f"{label}: outlives its parent ({p['name']}) without "
                "overhang_ok"
            )
    return problems


def serve_retire_violations(spans: List[dict]) -> List[str]:
    """Every replica-side ``serve`` subtree must contain EXACTLY one
    ``retire`` span: zero means a sequence vanished without teardown
    (leaked slot/pages), two means double-teardown (the double-free
    class I5 hunts).  Returns one message per violating subtree."""
    by_parent: Dict[int, List[dict]] = {}
    for s in spans:
        if s["parent"] is not None:
            by_parent.setdefault(s["parent"], []).append(s)

    def count_retires(span_id: int) -> int:
        n = 0
        stack = [span_id]
        while stack:
            for c in by_parent.get(stack.pop(), []):
                if c["name"] == "retire":
                    n += 1
                stack.append(c["span"])
        return n

    out = []
    for s in spans:
        if s["name"] != "serve":
            continue
        n = count_retires(s["span"])
        if n != 1:
            out.append(
                f"serve span {s['span']} (trace {s['trace']}): {n} retire "
                f"spans (want exactly 1)"
            )
    return out


def phase_durations(spans: List[dict]) -> Dict[str, float]:
    """Per-phase wall seconds of one trace's FIRST serve subtree — the
    TTFT decomposition bench.py reports.  Keys: the phase span names
    present (queue/station_wait/prefill/decode/...), plus ``first_step``
    (decode start → first token) when the decode span carries a
    ``first_token_t`` annotation."""
    serve = next((s for s in spans if s["name"] == "serve"), None)
    if serve is None:
        return {}
    children = [s for s in spans if s["parent"] == serve["span"]]
    out: Dict[str, float] = {}
    for s in children:
        if s["end"] is None or s["name"] == "retire":
            continue
        if s["name"] == "decode" and "first_token_t" in s["attrs"]:
            # TTFT decomposition stops at the first token; the rest of
            # the decode span is post-TTFT serving time
            first_t = s["attrs"]["first_token_t"]
            out["first_step"] = first_t - s["start"]
            out["decode"] = out.get("decode", 0.0) + (s["end"] - first_t)
        else:
            out[s["name"]] = out.get(s["name"], 0.0) + (
                s["end"] - s["start"]
            )
    return out
