"""Minimal protobuf wire-format codec (no generated code, no proto files).

The CRI shim (crishim/) must decode just enough of
``runtime.v1.RuntimeService/CreateContainer`` to find the pod and append
env/device entries.  Protobuf's wire format makes this safe without the
schema: unknown fields pass through untouched, and *appending* an encoded
repeated-field entry to a serialized message is exactly equivalent to adding
an element to that repeated field.  This replaces the reference's vendored
kubernetes/dockershim proto dependency (SURVEY.md §2 #8/#12) with ~100 lines
of wire handling.

Wire types used by CRI messages: 0 = varint, 2 = length-delimited.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple


def encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, object, Tuple[int, int]]]:
    """Yield (field_no, wire_type, value, (start, end) of the whole field).

    value is int for varints, bytes for length-delimited, raw bytes for
    fixed32/64 (returned but never produced by CRI paths we touch)."""
    pos = 0
    n = len(data)
    while pos < n:
        start = pos
        key, pos = decode_varint(data, pos)
        field_no, wire_type = key >> 3, key & 0x7
        if wire_type == 0:
            val, pos = decode_varint(data, pos)
        elif wire_type == 2:
            length, pos = decode_varint(data, pos)
            if pos + length > n:
                raise ValueError("truncated length-delimited field")
            val = data[pos : pos + length]
            pos += length
        elif wire_type == 5:
            val = data[pos : pos + 4]
            pos += 4
        elif wire_type == 1:
            val = data[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire_type}")
        yield field_no, wire_type, val, (start, pos)


def get_field(data: bytes, field_no: int) -> Optional[object]:
    for fn, _, val, _ in iter_fields(data):
        if fn == field_no:
            return val
    return None


def get_all(data: bytes, field_no: int) -> List[object]:
    return [val for fn, _, val, _ in iter_fields(data) if fn == field_no]


def encode_len_field(field_no: int, payload: bytes) -> bytes:
    return encode_varint((field_no << 3) | 2) + encode_varint(len(payload)) + payload


def encode_string_field(field_no: int, s: str) -> bytes:
    return encode_len_field(field_no, s.encode())


def decode_string_map(entries: List[object]) -> Dict[str, str]:
    """map<string,string> = repeated entry messages {key=1, value=2}."""
    out: Dict[str, str] = {}
    for e in entries:
        if not isinstance(e, (bytes, bytearray)):
            continue
        k = get_field(bytes(e), 1)
        v = get_field(bytes(e), 2)
        out[bytes(k).decode() if k else ""] = bytes(v).decode() if v else ""
    return out


def replace_field(data: bytes, field_no: int, new_payload: bytes) -> bytes:
    """Replace the FIRST occurrence of a length-delimited field in place
    (preserving unknown fields and ordering); append if absent."""
    for fn, wt, _, (start, end) in iter_fields(data):
        if fn == field_no and wt == 2:
            return data[:start] + encode_len_field(field_no, new_payload) + data[end:]
    return data + encode_len_field(field_no, new_payload)


def append_to_message_field(data: bytes, field_no: int, entries: List[bytes]) -> bytes:
    """Append encoded entries as new elements of a repeated message field —
    pure concatenation by protobuf wire semantics."""
    out = bytearray(data)
    for e in entries:
        out += encode_len_field(field_no, e)
    return bytes(out)


def encode_key_value(key: str, value: str) -> bytes:
    """CRI KeyValue {key=1, value=2} (also the shape of map entries)."""
    return encode_string_field(1, key) + encode_string_field(2, value)
