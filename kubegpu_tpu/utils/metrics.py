"""Tiny Prometheus-style metrics registry (SURVEY.md §5.5).

The reference had only glog verbosity; the rebuild's north-star metrics
(schedule-to-first-step latency, ICI-contiguous placement rate) need real
counters.  Text exposition format only — no client library dependency.

Three instrument kinds: counters (monotonic, ``inc``), gauges (set to the
current value, ``set_gauge`` — queue depth, live replicas), and histograms
(``observe`` — reservoir quantiles + exact count/sum).  All three take
labels as keyword arguments; a labeled series is independent of the
unlabeled one under the same name (``serve_ttft_seconds`` can split by
tenant or phase without disturbing the aggregate callers already read).

Exposition conformance: label values are escaped per the Prometheus text
format (``\\`` → ``\\\\``, ``"`` → ``\\"``, newline → ``\\n``), gauges and
histograms carry ``# TYPE`` lines, and ``render()`` output is stably
ordered (sorted by name then label set — independent of insertion order)
so scrapes diff cleanly and the conformance tests can parse it line by
line.  Histogram instruments expose ``_count``/``_sum`` plus reservoir
``{quantile=...}`` series — that sample shape IS Prometheus's
``summary`` type, so the TYPE line says ``summary`` (declaring
``histogram`` without ``_bucket``/``le="+Inf"`` samples is invalid under
strict/OpenMetrics parsers and would fail the whole scrape).

The canonical list of every metric name this codebase emits lives in
``utils/metric_names.py`` (lint-enforced); README "Observability"
documents each one.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Dict, List, Tuple

# Quantiles come from a bounded reservoir of the most recent observations;
# count/sum are exact running totals.  A long-lived extender must not grow
# (or re-sort) an unbounded list on the scheduling hot path.
RESERVOIR_SIZE = 1024

_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Dict[str, str]) -> _Key:
    return (name, tuple(sorted(labels.items())))


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash first (or
    the other escapes' backslashes would double-escape)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


class _Histogram:
    __slots__ = ("count", "total", "recent")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.recent: deque = deque(maxlen=RESERVOIR_SIZE)


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[_Key, float] = defaultdict(float)
        self._gauges: Dict[_Key, float] = {}
        self._histograms: Dict[_Key, _Histogram] = defaultdict(_Histogram)

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._counters[_key(name, labels)] += value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        """Gauges overwrite (current level, not a running total): queue
        depth, live-replica count — values that go down as well as up."""
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def gauge(self, name: str, **labels: str) -> float:
        with self._lock:
            return self._gauges.get(_key(name, labels), 0.0)

    @contextmanager
    def timer(self, name: str, **labels: str):
        """Observe the wall time of a ``with`` block into histogram
        ``name`` — the phase-timer idiom (e.g. speculative draft vs
        verify seconds); callers fencing device work must read the
        result back inside the block or the timer measures dispatch."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.observe(name, time.monotonic() - t0, **labels)

    def observe(self, name: str, value: float, **labels: str) -> None:
        with self._lock:
            h = self._histograms[_key(name, labels)]
            h.count += 1
            h.total += value
            h.recent.append(value)

    def get(self, name: str, **labels: str) -> float:
        with self._lock:
            return self._counters.get(_key(name, labels), 0.0)

    def quantile(self, name: str, q: float, **labels: str) -> float:
        """Reservoir quantile of a histogram series (0.0 if never
        observed) — the programmatic twin of the exposition lines, for
        bench rows and tests that assert on latency percentiles."""
        with self._lock:
            h = self._histograms.get(_key(name, labels))
            if h is None or not h.recent:
                return 0.0
            s = sorted(h.recent)
            return s[min(len(s) - 1, int(q * len(s)))]

    def histogram_count(self, name: str, **labels: str) -> int:
        with self._lock:
            h = self._histograms.get(_key(name, labels))
            return h.count if h is not None else 0

    def histogram_sum(self, name: str, **labels: str) -> float:
        """Exact running sum of a histogram series (0.0 if never
        observed) — with ``histogram_count`` it yields the mean, e.g.
        mean submit→first-chunk wait from ``serve_prefill_wait_seconds``."""
        with self._lock:
            h = self._histograms.get(_key(name, labels))
            return h.total if h is not None else 0.0

    def render(self) -> str:
        """Prometheus text exposition (stable-ordered: sorted by metric
        name, then label set)."""
        out: List[str] = []

        def line(name, labels, v):
            out.append(f"{name}{_label_str(labels)} {v}")

        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                line(name, labels, v)
            typed = set()
            for (name, labels), v in sorted(self._gauges.items()):
                if name not in typed:
                    # gauges carry an explicit TYPE line: a scraper must
                    # not apply rate() to them the way it does to the
                    # (untyped, counter-by-convention) names above
                    out.append(f"# TYPE {name} gauge")
                    typed.add(name)
                line(name, labels, v)
            typed = set()
            for (name, labels), h in sorted(self._histograms.items()):
                if name not in typed:
                    # count/sum/quantile samples are the SUMMARY shape;
                    # "histogram" would require _bucket/le series and
                    # fail strict parsers (see module docstring)
                    out.append(f"# TYPE {name} summary")
                    typed.add(name)
                out.append(f"{name}_count{_label_str(labels)} {h.count}")
                out.append(f"{name}_sum{_label_str(labels)} {h.total}")
                if h.recent:
                    s = sorted(h.recent)
                    for q in (0.5, 0.9, 0.99):
                        idx = min(len(s) - 1, int(q * len(s)))
                        qlabels = labels + (("quantile", str(q)),)
                        out.append(f"{name}{_label_str(qlabels)} {s[idx]}")
        return "\n".join(out) + "\n"


# process-global default registry
default_metrics = Metrics()
