"""Tiny Prometheus-style metrics registry (SURVEY.md §5.5).

The reference had only glog verbosity; the rebuild's north-star metrics
(schedule-to-first-step latency, ICI-contiguous placement rate) need real
counters.  Text exposition format only — no client library dependency.

Three instrument kinds: counters (monotonic, ``inc``), gauges (set to the
current value, ``set_gauge`` — queue depth, live replicas), and histograms
(``observe`` — reservoir quantiles + exact count/sum).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Dict, List, Tuple

# Quantiles come from a bounded reservoir of the most recent observations;
# count/sum are exact running totals.  A long-lived extender must not grow
# (or re-sort) an unbounded list on the scheduling hot path.
RESERVOIR_SIZE = 1024


class _Histogram:
    __slots__ = ("count", "total", "recent")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.recent: deque = deque(maxlen=RESERVOIR_SIZE)


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = defaultdict(float)
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._histograms: Dict[str, _Histogram] = defaultdict(_Histogram)

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        """Gauges overwrite (current level, not a running total): queue
        depth, live-replica count — values that go down as well as up."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def gauge(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key, 0.0)

    @contextmanager
    def timer(self, name: str):
        """Observe the wall time of a ``with`` block into histogram
        ``name`` — the phase-timer idiom (e.g. speculative draft vs
        verify seconds); callers fencing device work must read the
        result back inside the block or the timer measures dispatch."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.observe(name, time.monotonic() - t0)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._histograms[name]
            h.count += 1
            h.total += value
            h.recent.append(value)

    def get(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def quantile(self, name: str, q: float) -> float:
        """Reservoir quantile of a histogram (0.0 if never observed) —
        the programmatic twin of the exposition lines, for bench rows
        and tests that assert on latency percentiles."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None or not h.recent:
                return 0.0
            s = sorted(h.recent)
            return s[min(len(s) - 1, int(q * len(s)))]

    def histogram_count(self, name: str) -> int:
        with self._lock:
            h = self._histograms.get(name)
            return h.count if h is not None else 0

    def histogram_sum(self, name: str) -> float:
        """Exact running sum of a histogram (0.0 if never observed) —
        with ``histogram_count`` it yields the mean, e.g. mean
        submit→first-chunk wait from ``serve_prefill_wait_seconds``."""
        with self._lock:
            h = self._histograms.get(name)
            return h.total if h is not None else 0.0

    def render(self) -> str:
        """Prometheus text exposition."""
        out: List[str] = []

        def line(name, labels, v):
            if labels:
                lbl = ",".join(f'{k}="{val}"' for k, val in labels)
                out.append(f"{name}{{{lbl}}} {v}")
            else:
                out.append(f"{name} {v}")

        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                line(name, labels, v)
            typed = set()
            for (name, labels), v in sorted(self._gauges.items()):
                if name not in typed:
                    # gauges carry an explicit TYPE line: a scraper must
                    # not apply rate() to them the way it does to the
                    # (untyped, counter-by-convention) names above
                    out.append(f"# TYPE {name} gauge")
                    typed.add(name)
                line(name, labels, v)
            for name, h in sorted(self._histograms.items()):
                out.append(f"{name}_count {h.count}")
                out.append(f"{name}_sum {h.total}")
                if h.recent:
                    s = sorted(h.recent)
                    for q in (0.5, 0.9, 0.99):
                        idx = min(len(s) - 1, int(q * len(s)))
                        out.append(f'{name}{{quantile="{q}"}} {s[idx]}')
        return "\n".join(out) + "\n"


# process-global default registry
default_metrics = Metrics()
