"""L0 utilities (SURVEY.md §2 #10): API-server clients, logging helpers."""

from kubegpu_tpu.utils.apiserver import (
    ApiServer,
    Conflict,
    InMemoryApiServer,
    KubeApiServer,
    NotFound,
)
from kubegpu_tpu.utils.leaderelection import LeaderElector

__all__ = [
    "ApiServer",
    "Conflict",
    "InMemoryApiServer",
    "KubeApiServer",
    "LeaderElector",
    "NotFound",
]
