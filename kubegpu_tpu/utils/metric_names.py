"""The one catalog of every metric name this codebase emits.

Dashboards, alerts, README's "Observability" table, and the exposition
tests all reference metric names as strings; nothing ties them to the
emitting call sites — a rename in code silently breaks every consumer.
This module is the tie: each emitted name appears EXACTLY once below
with its instrument type, label keys, and one-line meaning, and
``tests/test_metrics_catalog.py`` greps ``kubegpu_tpu/`` for
``inc(``/``observe(``/``set_gauge(``/``timer(`` string literals and
fails the build on any name missing here (and on any catalog entry no
code emits — a stale catalog is drift too).

Conventions (Prometheus): ``*_total`` counters, ``*_seconds`` histograms
of wall time, gauges for current levels.  Histograms render as
``<name>_count`` / ``<name>_sum`` plus reservoir ``quantile`` series —
see utils/metrics.py.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple


class MetricSpec(NamedTuple):
    type: str                    # "counter" | "gauge" | "histogram"
    labels: Tuple[str, ...]      # label KEYS the emitting sites attach
    help: str


def _c(labels: Tuple[str, ...], help: str) -> MetricSpec:
    return MetricSpec("counter", labels, help)


def _g(labels: Tuple[str, ...], help: str) -> MetricSpec:
    return MetricSpec("gauge", labels, help)


def _h(labels: Tuple[str, ...], help: str) -> MetricSpec:
    return MetricSpec("histogram", labels, help)


CATALOG: Dict[str, MetricSpec] = {
    # -- scheduler / control plane (scheduler/core.py, utils/apiserver.py)
    "kubegpu_filter_total": _c((), "extender Filter calls"),
    "kubegpu_filter_seconds": _h((), "Filter handler wall time"),
    "kubegpu_prioritize_total": _c((), "extender Prioritize calls"),
    "kubegpu_prioritize_seconds": _h((), "Prioritize handler wall time"),
    "kubegpu_bind_total": _c((), "extender Bind calls"),
    "kubegpu_bind_seconds": _h((), "Bind handler wall time"),
    "kubegpu_bind_conflicts_total": _c(
        (), "binds refused by optimistic-concurrency conflicts"),
    "kubegpu_chips_allocated_total": _c((), "chips handed to pods"),
    "kubegpu_grouped_allocated_total": _c(
        (), "chips allocated via gang (grouped) admission"),
    "kubegpu_placements_total": _c((), "successful placements"),
    "kubegpu_placements_contiguous_total": _c(
        (), "placements that were ICI-contiguous"),
    "kubegpu_preemptions_total": _c((), "preemption plans executed"),
    "kubegpu_preempted_pods_total": _c((), "pods evicted by preemption"),
    "kubegpu_stranded_gang_rollbacks_total": _c(
        (), "partially-bound gangs rolled back after the grace window"),
    "kubegpu_health_evictions_total": _c(
        (), "assignments evicted because their chips disappeared"),

    # -- gateway control plane (gateway/core.py, failover.py, router.py)
    "gateway_requests_total": _c(
        ("outcome",), "terminal results by outcome (ok/rejected/error/"
        "timeout)"),
    "gateway_queue_depth": _g((), "admission queue depth"),
    "gateway_queue_wait_seconds": _h(
        (), "enqueue -> dispatcher pickup wait"),
    "gateway_ttft_seconds": _h(
        ("role",), "enqueue -> full response (unary data plane: TTFT == "
        "TTLT).  Emitted twice per ok request: unlabeled aggregate (the "
        "FleetObserver's window diffs) plus role=colocated|disaggregated "
        "(disaggregated = prefilled on one replica, decoded on another "
        "after the post-prefill handoff)"),
    "gateway_itl_seconds": _h(
        ("role",), "mean inter-token latency per ok request (total / "
        "tokens).  Unlabeled aggregate plus role=colocated|disaggregated "
        "— the pair the disaggregation bench gates on (decode iterations "
        "no longer stalled behind long prefills)"),
    "gateway_phase_handoff_total": _c(
        ("outcome",), "post-prefill KV handoffs by outcome (ok = decode "
        "replica took the sequence; fallback = decode side refused/died "
        "and the prefill replica resumed decode locally; failed = "
        "neither leg landed, normal failover re-dispatched cold)"),
    "gateway_phase_handoff_seconds": _h(
        (), "sealed announcement -> handoff dispatched (export + "
        "re-home + import kickoff wall time)"),
    "gateway_phase_handoff_overlap_seconds": _h(
        (), "per streamed handoff: delta-shipping wall time that ran "
        "DURING prefill compute instead of on the critical path (large "
        "overlap vs gateway_phase_handoff_seconds = handoff-bound TTFT "
        "pressure; the ratio actuator reads the difference)"),
    "gateway_phase_handoff_deltas_total": _c(
        (), "sealed-page deltas acked by the decode side mid-prefill "
        "(the seal-watch pipeline; 0 under stream_handoff=False)"),
    "gateway_phase_handoff_wire_bytes_total": _c(
        ("mode",), "serialized KV payload bytes shipped by post-prefill "
        "handoffs, mode=streamed|oneshot (streamed = final cursor "
        "export after >=1 acked delta; int8 pools halve this per page "
        "vs bf16)"),
    "gateway_live_replicas": _g((), "replicas routable right now"),
    "gateway_deadline_exceeded_total": _c(
        (), "requests failed by the end-to-end deadline"),
    "gateway_failures_total": _c(
        (), "requests failed after exhausting attempts"),
    "gateway_retries_total": _c((), "re-dispatches after a failed attempt"),
    "gateway_retry_budget_exhausted_total": _c(
        (), "retries refused by the retry budget"),
    "gateway_hedges_total": _c((), "hedged (duplicate) dispatches issued"),
    "gateway_hedge_wasted_total": _c(
        (), "hedge attempts cancelled because another attempt won"),
    "gateway_duplicate_results_total": _c(
        (), "second terminal results dropped by exactly-once delivery"),
    "gateway_session_repin_total": _c(
        (), "session re-pins after the pinned replica drained (KV loss "
        "unless a sealed-export restore made the move a transfer — see "
        "gateway_session_restores_total)"),
    "gateway_session_restores_total": _c(
        (), "sealed-chain KV restores imported into a re-pin target "
        "before dispatch (the turn-2 state survived the replica)"),
    "gateway_migrations_total": _c(
        ("outcome",), "live KV-page migrations by outcome (ok = handoff "
        "dispatched; export_failed = source refused/unreachable; "
        "import_refused = target refused the payload)"),
    "gateway_replica_drains_total": _c(
        (), "graceful replica drains started (DRAINING -> released "
        "lifecycles)"),
    "gateway_shed_total": _c(
        ("reason",), "requests shed with an explicit retryable "
        "backpressure result instead of being served (brownout = the "
        "overload ladder's level-3 admission shed of lowest-priority/"
        "over-quota tenants; deadline_expired = shed-before-work, a "
        "request whose deadline lapsed while queued was never "
        "dispatched to burn prefill)"),
    "gateway_brownout_level": _g(
        (), "overload brownout ladder rung in force (0 none; 1 hedging "
        "disabled; 2 + speculation shrunk; 3 + tenant shedding) — set "
        "by the fleet controller when capacity cannot arrive in time"),

    # -- session-KV store, gateway side (gateway/sessionstore.py):
    #    degradation accounting for the external insurance store
    "gateway_session_store_degraded_total": _c(
        ("reason",), "sessions degraded to cold prefill by store "
        "trouble, by reason (unreachable = store down/breaker open; "
        "cas_conflict = a capture lost its versioned put race; "
        "lease_expired = the session's store lease lapsed).  Never a "
        "request error — degradation IS the contract"),
    "gateway_session_store_retries_total": _c(
        (), "store op retries after a transport failure (bounded, "
        "exponential backoff + jitter)"),
    "gateway_session_store_fastfail_total": _c(
        (), "store ops fast-failed by the open circuit breaker (a dead "
        "store costs microseconds per op, not a deadline)"),
    "gateway_session_store_capture_drops_total": _c(
        (), "queued sealed-KV captures dropped oldest-first by the "
        "bounded async write-through queue (capture is insurance, "
        "never admission-blocking)"),

    # -- session-KV store, server side (gateway/sessionstore.py
    #    StoreServer — the standalone store pod's own /metrics)
    "session_store_requests_total": _c(
        ("verb",), "store requests by verb (get/put/list/mark/delete)"),
    "session_store_cas_conflicts_total": _c(
        (), "versioned puts refused because the session's version "
        "moved (a stale capture losing to a newer seal — the two-"
        "gateway race the CAS exists for)"),
    "session_store_lease_expired_total": _c(
        (), "sessions dropped because their lease lapsed (reads after "
        "expiry answer 404 reason=lease_expired; the gateway degrades "
        "to cold prefill)"),
    "session_store_payloads_dropped_total": _c(
        (), "sealed-KV payloads evicted oldest-first by the byte-"
        "bounded LRU (streams stay; those sessions restore cold)"),
    "session_store_sessions": _g((), "session entries resident"),
    "session_store_payload_bytes": _g(
        (), "total retained sealed-KV payload bytes (bounded by "
        "--max-payload-bytes, default 256 MiB)"),
    "session_store_payload_dedup_total": _c(
        (), "sealed-KV payload writes answered by an existing content-"
        "addressed record instead of a second copy (a payload captured "
        "by N sessions and published as a prefix rests ONCE; refcounts "
        "track the sharers)"),
    "session_store_prefixes": _g(
        (), "fleet prefix chains resident in the store's prefix "
        "namespace"),
    "session_store_prefix_evicted_total": _c(
        (), "prefix chains evicted by the popularity-weighted LRU "
        "(fewest probe hits first, oldest touch breaking ties) or "
        "reaped by the idle lease — prefixes are immortal only while "
        "hot, never leased like sessions"),
    "prefix_tier_resident_bytes": _g(
        (), "payload bytes the prefix namespace keeps resident (its "
        "share of the content-addressed payload table; bounded by "
        "--max-prefix-bytes)"),

    # -- fleet-wide shared-prefix KV tier (gateway/prefixtier.py,
    #    router.py): prefill any hot prefix once, ever
    "gateway_prefix_tier_hits_total": _c(
        (), "admission-time tier probes that found a stored chain "
        "sharing at least one full page with the prompt (the payload "
        "then imports into the target replica before prefill)"),
    "gateway_prefix_tier_misses_total": _c(
        (), "tier probes that found no stored prefix (cold prefill as "
        "usual; the completion's publish may seed the tier for the "
        "next caller)"),
    "gateway_prefix_tier_publishes_total": _c(
        (), "sealed chains published into the tier's prefix namespace "
        "(post-dedup: the gateway's published-set and the store's "
        "metadata-first probe both gate re-uploads)"),
    "gateway_prefix_tier_imports_total": _c(
        (), "tier payloads imported into a replica's PrefixPageCache "
        "before prefill (the fleet-warm pages the admission then hits)"),
    "gateway_prefix_tier_degraded_total": _c(
        ("reason",), "tier ops resolved as counted cold prefill by "
        "store trouble, by reason (unreachable = store down/breaker "
        "open; error = malformed or refused op).  Never a request "
        "error — the session-store degradation contract, applied to "
        "prefixes"),
    "gateway_prefix_tier_publish_drops_total": _c(
        (), "queued publishes dropped oldest-first by the bounded "
        "async publish queue (publishing is opportunistic seeding, "
        "never result-path-blocking)"),
    "gateway_prefix_route_warm_total": _c(
        (), "requests the PrefixLocalityRouter routed by longest "
        "locally-warm prefix instead of the consistent-hash ring "
        "fallback (agent fleets packing onto warm replicas)"),

    # -- gateway streaming pass-through (gateway/server.py, failover.py)
    "gateway_stream_requests_total": _c(
        (), "streaming (SSE) /v1/generate requests accepted"),
    "gateway_stream_tokens_total": _c(
        (), "tokens relayed to streaming callers as they came off "
        "replicas (the terminal result stays authoritative)"),
    "gateway_stream_disconnects_total": _c(
        (), "streaming callers that vanished mid-stream; each one "
        "cancelled its in-flight attempts wire-level (replica pages "
        "freed)"),
    "gateway_stream_hedges_total": _c(
        (), "hedged dispatches issued for STREAMING requests — safe "
        "because the StreamRelay dedups twin streams by token index; "
        "greedy and seed-pinned sampled streams hedge, unpinned "
        "sampled streams never do"),
    "gateway_sampled_hedges_total": _c(
        (), "hedged dispatches issued for SAMPLED (temperature > 0) "
        "requests — possible only because the request pinned a seed, "
        "making every replica's sampled stream byte-identical"),
    "gateway_stream_dedup_tokens_total": _c(
        (), "tokens a streaming attempt delivered that the caller "
        "already had (hedge twin / retry overlap) — dropped by the "
        "relay's dedup watermark, never surfaced twice"),

    # -- gateway tier (gateway/tier.py): the N-gateway scale-out layer
    "gateway_tier_gateways": _g(
        (), "gateways alive in this tier right now"),
    "gateway_tier_deaths_total": _c(
        (), "gateway instances killed (chaos or crash); their in-flight "
        "attempts cancel wire-level and their pendings fail with the "
        "retryable 'gateway died' error"),
    "gateway_tier_retries_total": _c(
        (), "tier-client retries of a request on a sibling gateway "
        "after its home gateway died (same request_id; replica-side "
        "duplicate-id eviction keeps at most one live stream)"),

    # -- replica HTTP serving endpoint (gateway/dataplane.py): the
    #    pod-side half of the distributed data plane
    "replica_http_requests_total": _c(
        ("verb",), "replica endpoint requests by verb "
        "(submit/cancel/state/get)"),
    "replica_http_stream_events_total": _c(
        (), "SSE data events written to submit streams "
        "(tokens/done/error; pings not counted)"),
    "replica_http_streams_active": _g(
        (), "submit streams currently open (one per in-flight remote "
        "request)"),
    "replica_http_cancels_total": _c(
        (), "sequences cancelled wire-level (/v1/cancel, or a "
        "duplicate-id eviction)"),
    "replica_http_disconnect_cancels_total": _c(
        (), "sequences cancelled because their stream's client "
        "vanished mid-stream (disconnect ⇒ cancel; pages freed)"),
    "replica_migrate_pages_total": _c(
        ("dir",), "KV pages moved through the migration verbs by "
        "direction (export: serialized out of this pool; import: "
        "written into it)"),
    "replica_migrate_seconds": _h(
        ("dir",), "wall time of one export/import verb (serialize + "
        "detach, or allocate + chain-replay + resume)"),
    "replica_migrate_wire_bytes_total": _c(
        ("dir",), "encoded transfer payload bytes through the "
        "migration verbs by direction"),
    "replica_http_expired_refusals_total": _c(
        (), "admissions the replica refused because the remaining "
        "deadline the gateway shipped on the wire elapsed while the "
        "request queued in the serving loop's inbox (shed-before-work, "
        "replica side: no prefill burned for an abandoned caller)"),
    "replica_stream_fastforward_tokens_total": _c(
        (), "tokens a submit's resume watermark told this replica NOT "
        "to emit (the caller already has them — hedge twins and "
        "gateway-failover resumes decode them but fast-forward "
        "emission)"),

    # -- fleet controller (controller/): the serving↔scheduling loop
    "controller_reconciles_total": _c((), "reconcile ticks run"),
    "controller_pressure": _g(
        (), "EWMA-smoothed SLO pressure: max(backlog (queued + "
        "in-flight) / per-replica target, recent TTFT / target) — the "
        "one number the scale and brownout thresholds judge"),
    "controller_serving_replicas": _g(
        (), "routable serving replicas observed this tick"),
    "controller_desired_replicas": _g(
        (), "replica count this tick's decision aims at (observed "
        "+/- 1; the loop reshapes one step at a time)"),
    "controller_draining_replicas": _g(
        (), "replicas mid-drain (DRAINING, not yet released)"),
    "controller_fleet_util": _g(
        (), "max replica token-budget saturation from the per-step "
        "ledgers (0 when replicas keep no ledger)"),
    "controller_scale_events_total": _c(
        ("dir",), "fleet reshape decisions by direction (up = a new "
        "serving pod gang-scheduled, preempting batch if needed; down "
        "= a replica drained before its chips release to batch)"),
    "controller_scale_up_failed_total": _c(
        (), "scale-ups that found no placement even with preemption "
        "(arms the brownout ladder: capacity cannot arrive in time)"),
    "controller_releases_total": _c(
        (), "drained replicas released (pod deleted, chips returned "
        "to the pool) — exactly once per drain, restarts included"),
    "controller_requeued_pods_total": _c(
        (), "preempted batch pods checkpointed and recreated PENDING "
        "(checkpoint-and-requeue; they re-bind when chips free up)"),
    "controller_drains_resumed_total": _c(
        (), "in-progress drains adopted by a restarted controller "
        "(re-derived from the registry's DRAINING marks)"),
    "controller_brownout_level": _g(
        (), "brownout rung the controller currently holds the "
        "gateway(s) at (mirrors gateway_brownout_level)"),
    "controller_role_reshapes_total": _c(
        ("dir",), "prefill:decode ratio actuator decisions (prefill = "
        "a flex replica re-roled toward prefill under TTFT pressure; "
        "decode = one returned toward decode under ITL pressure; "
        "collapse = disaggregation folded back to co-located because "
        "handoff capacity was the bottleneck)"),
    "controller_prefill_replicas": _g(
        (), "replicas currently holding the prefill role"),
    "controller_handoff_exposed_tax_s": _g(
        (), "this tick's mean CRITICAL-PATH handoff seconds per "
        "handoff — window diff of gateway_phase_handoff_seconds minus "
        "gateway_phase_handoff_overlap_seconds.  Large while TTFT is "
        "hot = handoff-bound pressure: the ratio actuator holds the "
        "flex->prefill flip (more prefill bandwidth cannot shrink the "
        "transfer tail)"),

    # -- serving data plane (models/serving.py, models/paging.py)
    "serve_ttft_seconds": _h((), "submit -> first generated token"),
    "serve_itl_seconds": _h((), "inter-token latency between emits"),
    "serve_phase_seconds": _h(
        ("phase",), "per-request phase wall time from the span tree "
        "(queue/station_wait/prefill/first_step/decode); emitted only "
        "when tracing is enabled"),
    "serve_prefill_chunks_total": _c((), "prefill chunk programs run"),
    "serve_prefill_wait_seconds": _h(
        (), "submit -> first prefill chunk (station wait included)"),
    "serve_station_slots_busy": _g(
        (), "prefill-station slots occupied by in-flight admissions"),
    "serve_prompt_tokens_total": _c((), "prompt tokens admitted"),
    "serve_prefix_hit_tokens_total": _c(
        ("kind",), "prompt tokens skipped via prefix-cache hits, split "
        "by hit-page kind (prompt/decode); labeled series only — sum "
        "over the label for the total"),
    "serve_decode_pages_sealed_total": _c(
        (), "decode-produced pages sealed into the prefix cache at "
        "retirement"),
    "serve_handoff_pages_reclaimed_total": _c(
        (), "prompt pages freed EARLY on a parked prefill replica — "
        "acked by the decode side's staged deltas, released before the "
        "final handoff roundtrip (each reclaim raises prefill admission "
        "headroom mid-schedule)"),
    "serve_spec_steps_total": _c((), "speculative verify iterations"),
    "serve_spec_tokens_per_step": _c(
        (), "tokens committed by speculative verifies (divide by "
        "serve_spec_steps_total for the per-step mean)"),
    "serve_spec_accept_rate": _h(
        ("mode",), "accepted-draft fraction per slot per verify "
        "(e-1)/k; mode=greedy (exact-match verify) or mode=sampled "
        "(rejection-sampled lossless speculation)"),
    "serve_spec_draft_seconds": _h((), "draft proposal program wall time"),
    "serve_spec_verify_seconds": _h((), "verify program wall time"),
    "serve_draft_cache_rows": _g(
        (), "draft ring-cache rows resident (slots x draft_window)"),
    "serve_draft_ring_bytes": _g(
        ("dtype",), "draft ring-cache bytes RESTING by storage dtype "
        "(mesh-wide aggregate, like serve_pool_kv_bytes).  A quantized "
        "ring (kv_dtype=\"int8\") reports two series — int8 row bytes "
        "and float32 per-(slot, head) scale bytes; a full-width ring "
        "one series at its compute dtype.  The freed difference is "
        "admission headroom the page pool gets back"),

    # -- per-iteration serving ledger (PagedContinuousBatcher.serve_step;
    #    the gauge twin of the bounded ledger ring at /debug/trace)
    "serve_step_rows": _g(
        (), "rows processed by the last serving iteration (decode tokens"
        " + prefill chunk rows) against token_budget"),
    "serve_step_host_ms": _g(
        (), "host-side bookkeeping time of the last serving iteration "
        "(overlaps device compute under pipelined decode)"),
    "serve_step_device_ms": _g(
        (), "time the last serving iteration spent BLOCKED on the "
        "device token readback (near zero when pipelining hides it)"),
    "serve_pool_pages_free": _g(
        (), "KV pool pages on the free list (mesh-wide count under "
        "tensor parallelism: tables replicate, a page spans every "
        "shard)"),
    "serve_pool_pages_live": _g(
        (), "KV pool pages privately held by live sequences (mesh-wide "
        "count under tensor parallelism)"),
    "serve_pool_pages_cached": _g(
        (), "KV pool pages resident in the prefix cache (shared or "
        "idle-evictable; mesh-wide count under tensor parallelism)"),

    # -- quantized KV page pool (models/paging.py kv_dtype="int8"):
    #    per-dtype byte economy + measured quantization quality
    "serve_pool_kv_bytes": _g(
        ("dtype",), "KV page pool bytes RESTING by storage dtype "
        "(mesh-wide aggregate, like the page counts; per-device is "
        "serve_tp_pool_bytes_per_device).  A quantized pool reports "
        "two series — int8 page bytes and float32 scale bytes; a "
        "full-width pool one series at its compute dtype.  The gauge "
        "the int8 capacity claim (2x rows per byte budget) is audited "
        "against"),
    "serve_kv_quant_seal_requants_total": _c(
        (), "pool pages run through seal-time requantization before "
        "entering the shared prefix chain (int8 pool: stretch int8 "
        "range back to 127, shrink the scale — recovers precision a "
        "rejected speculative row's grow-and-rescale inflation "
        "squeezed out; a no-op for already-tight pages)"),
    "serve_kv_quant_agreement": _g(
        (), "measured token agreement of the int8 pool vs the "
        "full-width pool on identical traffic (bench.py "
        "serving_quantized_pool; models/serving.record_quant_quality)"),
    "serve_kv_quant_divergence_margin": _g(
        (), "top1-top2 logit margin at the first int8-vs-full-width "
        "token divergence (near-tie ⇒ the expected quantization "
        "rounding class; a wide margin would mean a real bug)"),
    "serve_kv_quant_ppl_delta": _g(
        (), "teacher-forced eval NLL delta of the int8-pool stream vs "
        "the full-width pool's (the eval_ppl_delta_int8 discipline "
        "applied to the page pool)"),

    # -- sampled-speculation quality (bench.py serving_sampled_spec;
    #    models/serving.record_sampling_quality): the statistical gate
    #    for LOSSLESS rejection-sampled speculation — per-position
    #    acceptance plus distribution-agreement evidence that the
    #    spec-sampled stream is the target model's own
    "serve_sampled_accept_rate": _g(
        ("lane",), "mean accepted-draft fraction of the sampled-"
        "speculation bench lane ((emitted-1)/k averaged over verifies); "
        "lane=dense (slot batcher) or lane=paged (page-pool batcher)"),
    "serve_sampled_nll_delta": _g(
        ("lane",), "teacher-forced target-model NLL of the spec-sampled "
        "streams minus the plain-sampled streams' (same seeds; ~0 "
        "within sampling noise when rejection sampling is lossless); "
        "lane=dense|paged"),
    "serve_sampled_unigram_agreement": _g(
        ("lane",), "L1 overlap of the unigram token histograms of the "
        "spec-sampled vs plain-sampled streams (1.0 = identical "
        "marginal distributions; a distribution-level lossless check); "
        "lane=dense|paged"),

    # -- tensor-parallel serving (models/paging.py with a mesh): the
    #    per-DEVICE half of the pool economy plus the collective traffic
    #    the Megatron psums cost per iteration
    "serve_tp_devices": _g(
        (), "tensor-parallel width of the serving mesh (1 = unsharded)"),
    "serve_tp_pool_bytes_per_device": _g(
        (), "KV pool bytes RESTING per device (the aggregate pool "
        "divided by the tensor-parallel width — heads shard 1/tp of "
        "every page)"),
    "serve_tp_collective_bytes_total": _c(
        (), "modeled per-device all-reduce wire bytes of the serving "
        "iterations' TP psums (2 per transformer block: o_proj + "
        "mlp_down; ring cost 2*(tp-1)/tp of the payload; 0 at tp=1)"),
}


def assert_known(name: str) -> None:
    """Raise if a metric name is not in the catalog (tests' helper)."""
    if name not in CATALOG:
        raise KeyError(
            f"metric {name!r} is not in utils/metric_names.CATALOG — add "
            "it (type, labels, help) or fix the emitting call site"
        )
