"""Scheduler core: the filter / prioritize / bind verbs (SURVEY.md §3.1).

Pure-ish orchestration over ClusterCache + grpalloc + PodGroupRegistry; the
HTTP layer (server.py) is a thin codec around this so every scheduling
behavior is testable without sockets (SURVEY.md §4 "multi-node without a
cluster").

Flow per verb:
- filter: non-TPU pods pass everywhere (BASELINE config 1); gang pods pass
  only on their plan's node (planning + reservation happen here); plain TPU
  pods pass where pod_fits_group_constraints fits.
- prioritize: placement score per node, rescaled to the extender's 0-10.
- bind: re-fit under the cache lock (assume-then-commit), write the
  assignment annotation, then the binding; any API failure rolls the
  reservation back (SURVEY.md §3.1 failure containment).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubegpu_tpu.scheduler.cache import ClusterCache
from kubegpu_tpu.scheduler.plugins import (
    DeviceSchedulerPlugin,
    PluginRegistry,
    default_registry,
)
from kubegpu_tpu.scheduler.podgroup import PodGroupRegistry
from kubegpu_tpu.scheduler.preemption import collect_units, find_victims
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.info import Assignment, PodInfo, TpuRequest
from kubegpu_tpu.types.topology import is_contiguous_submesh
from kubegpu_tpu.utils.apiserver import ApiServer, Conflict, NotFound
from kubegpu_tpu.utils.events import EventRecorder
from kubegpu_tpu.utils.metrics import Metrics, default_metrics

log = logging.getLogger(__name__)


@dataclass
class FilterResult:
    nodes: List[str] = field(default_factory=list)
    failed: Dict[str, str] = field(default_factory=dict)
    error: str = ""
    # at least one node failed for a capacity-shaped reason (internal;
    # not part of the HTTP response)
    capacity_failure: bool = False


def _scale_scores(raw: List[Tuple[str, Optional[float]]]) -> List[Tuple[str, int]]:
    """Map grpalloc's 0-100 scores onto the extender's 0-10 rank-preserving
    PER CANDIDATE SET: min-max stretch the fitting nodes across 1..10, so
    the aspect/anti-fragmentation distinctions (weights 15/25 of the raw
    score) survive the 11-bucket quantization instead of vanishing in a
    global round(/10).  Non-fitting nodes score 0, strictly below every
    fitting node; the best fitting node always scores 10."""
    fitting = [s for _, s in raw if s is not None]
    if not fitting:
        return [(n, 0) for n, _ in raw]
    lo, hi = min(fitting), max(fitting)
    span = hi - lo

    def scale(s: Optional[float]) -> int:
        if s is None:
            return 0
        if span <= 0:
            return 10
        return 1 + round(9 * (s - lo) / span)

    return [(n, scale(s)) for n, s in raw]


def _consensus_size(sizes: List[int]) -> int:
    """Gang cardinality by member consensus: the most common declared
    pod-group-size wins; ties break toward the SMALLER size (the direction
    that avoids rolling back a healthy gang).  Never last-write-wins — one
    recreated member with a stale annotation must not move the
    denominator the stranded-gang rollback is judged against."""
    if not sizes:
        return 0
    counts: Dict[int, int] = {}
    for s in sizes:
        counts[s] = counts.get(s, 0) + 1
    top = max(counts.values())
    return min(s for s, c in counts.items() if c == top)


# incarnation consensus (_consensus_size's string analog) lives in
# podgroup.gang_arithmetic now, applied inside the one shared formula


class Scheduler:
    def __init__(
        self,
        api: ApiServer,
        cache: Optional[ClusterCache] = None,
        metrics: Optional[Metrics] = None,
        gang_plan_ttl_s: float = 120.0,
        plugins: Optional[PluginRegistry] = None,
        evict_on_chip_failure: bool = True,
        absent_grace: int = 2,
        stranded_grace: int = 5,
        active_preemption: bool = True,
        preemption_min_runtime_s: float = 0.0,
    ) -> None:
        self.api = api
        self.cache = cache or ClusterCache(api)
        self.groups = PodGroupRegistry(self.cache, plan_ttl_s=gang_plan_ttl_s)
        self.metrics = metrics or default_metrics
        # operator-facing decision records (kubectl describe pod), the
        # kube-scheduler convention: best-effort, deduped, never failing
        # a verb (utils/events.py)
        self.events = EventRecorder(api)
        # device-type dispatch (SURVEY.md §2 #5): TPU built-in; more device
        # plugins via PluginRegistry.load (the Go-plugin .so analog)
        self.plugins = plugins or default_registry()
        # elastic recovery (SURVEY.md §5.3): a pod whose chips die is
        # evicted so its controller recreates it and it re-schedules onto
        # healthy chips (gang members rejoin their gang's slice layout)
        self.evict_on_chip_failure = evict_on_chip_failure
        # True: filter evicts lower-priority victims itself and re-plans in
        # the same verb (fastest admission).  False: filter only REPORTS
        # capacity failure and nominations flow through the advisory
        # /preemption verb — kube-scheduler performs the evictions (the
        # classic extender division of labor its preemptVerb config exists
        # for).  Both are deployed modes; deploy/device-scheduler.yaml
        # documents the flag.
        self.active_preemption = active_preemption
        # Anti-starvation min-runtime shield: a unit (pod or whole gang)
        # whose newest member durably bound within this window is
        # NON-preemptible — two higher-priority tenants alternately
        # preempting a low-priority gang would otherwise starve it
        # forever; the shield guarantees every admission this much
        # runtime, bounding starvation to admission-rate x window.  The
        # bind time rides the assignment annotation, so the shield
        # survives scheduler restarts.  0 disables (every admitted unit
        # is immediately preemptible — the pre-r4 behavior).
        self.preemption_min_runtime_s = preemption_min_runtime_s
        # HA fencing re-check (set by ExtenderServer when leader election
        # is on): consulted immediately before bind's durable annotation
        # write, so a verb that slipped through the gate as the lease
        # window closed aborts instead of committing over a promoted
        # standby's allocations.  A Lease is not a true fencing token — a
        # PATCH already in flight can still land late; the conflict
        # sweep's AssignmentConflict eviction resolves that residue.
        self.serving_gate = None
        # Eviction is irreversible, but "chip absent from an advertisement"
        # and "node missing from a LIST" are not — a restarting advertiser
        # or one truncated enumeration must not destroy a healthy running
        # gang.  An explicitly-Unhealthy chip evicts immediately (positive
        # signal); a merely-ABSENT chip or a vanished node must stay
        # absent/vanished for `absent_grace` consecutive observations first.
        self.absent_grace = max(1, absent_grace)
        # Incomplete-gang rollback: a gang holding SOME bound members but
        # not all for this many consecutive resyncs leaks capacity (its
        # pending members may never fit again once other tenants take the
        # chips) — all-or-nothing applies to the admission OUTCOME, so the
        # bound members are rolled back and the controller re-admits the
        # whole gang atomically.  Longer than the plan TTL (120 s / 30 s
        # resyncs) so an actively-binding gang is never rolled back.
        self.stranded_grace = max(1, stranded_grace)
        # (pod key, node, device_index) -> (strikes, advertisement fingerprint)
        self._absent_chip_strikes: Dict[tuple, Tuple[int, str]] = {}
        # (pod key, node) -> consecutive resyncs the node was missing
        self._missing_node_strikes: Dict[tuple, int] = {}
        # pod key -> consecutive resyncs its record stayed conflict-dropped
        self._conflict_strikes: Dict[str, int] = {}
        # gang key -> (consecutive no-progress resyncs, bound-member set)
        self._stranded_strikes: Dict[str, tuple] = {}
        # gangs already warned about over-subscribed done arithmetic: the
        # condition persists across resyncs and must not log every tick
        self._suspect_warned: set = set()
        # serializes the failure-detector entry points: the resync thread
        # and the node-watch thread both mutate the strike maps and run the
        # eviction sweep — unserialized, the watch can resize a dict mid-
        # iteration or double-evict one victim
        self._lifecycle_lock = threading.Lock()

    # -- filter -----------------------------------------------------------
    def filter(self, pod_obj: dict, node_names: List[str]) -> FilterResult:
        t0 = time.monotonic()
        try:
            pod = annotations.pod_from_k8s(pod_obj)
        except Exception as e:  # noqa: BLE001 - a malformed pod must not 500
            return FilterResult(error=f"unparseable pod: {e}")
        try:
            result = self._filter(pod, node_names)
            return result
        finally:
            self.metrics.inc("kubegpu_filter_total")
            self.metrics.observe("kubegpu_filter_seconds", time.monotonic() - t0)

    # -- plugin dispatch ---------------------------------------------------
    def _owning_plugin(self, pod: PodInfo):
        """(plugin, error): the single plugin serving this pod.  A pod mixing
        device types is an error — fitting only one type would silently
        over-commit the others."""
        owners = self.plugins.plugins_for(pod)
        if not owners:
            return None, None
        if len(owners) > 1:
            names = "+".join(p.name for p in owners)
            return None, (
                f"pod requests multiple device types ({names}); "
                "one device type per pod is supported"
            )
        return owners[0], None

    @staticmethod
    def _is_tpu_gang(pod: PodInfo) -> bool:
        """Gang planning is TPU-only (it reasons in mesh rectangles); a
        generic-device pod with gang annotations schedules plain.  The ONE
        definition used by filter, prioritize and bind — they must agree."""
        return bool(pod.pod_group) and pod.total_tpu_chips() > 0

    def _filter(self, pod: PodInfo, node_names: List[str]) -> FilterResult:
        plugin, err = self._owning_plugin(pod)
        if err:
            return FilterResult(failed={n: err for n in node_names})
        if plugin is None:
            # no device request any plugin owns: every node is fine by us
            return FilterResult(nodes=list(node_names))

        if self._is_tpu_gang(pod):
            outcome = self.groups.plan_for(pod) or None
            if outcome is None:
                planned = self.groups.try_plan(pod)
                if (
                    planned.plan is None
                    and planned.capacity_failure
                    and self.active_preemption
                ):
                    # multi-tenant path (BASELINE config 5): evict strictly
                    # lower-priority units, then re-plan once
                    if self._attempt_preemption(pod, self._slices_of(node_names)):
                        planned = self.groups.try_plan(pod)
                if planned.plan is None:
                    self.events.pod_event(
                        pod.namespace, pod.name, "GangUnschedulable",
                        planned.reason, type_="Warning", uid=pod.uid,
                    )
                    return FilterResult(
                        failed={n: planned.reason for n in node_names},
                        error="",
                    )
                outcome = planned.plan
                self.events.pod_event(
                    pod.namespace, pod.name, "GangPlanned",
                    (
                        f"gang {outcome.group} planned: "
                        f"{len(outcome.per_pod)} member(s) reserved, "
                        f"score {outcome.score:.1f}"
                    ),
                    uid=pod.uid,
                )
            planned_slice = outcome.per_pod[pod.key].slice_id
            if pod.slice_selector is not None and (
                planned_slice is None
                or planned_slice not in pod.slice_selector
            ):
                # planning used the FIRST member's selector; a member whose
                # OWN selector excludes the planned slice (mixed-selector
                # gang, or a recreated member with a new annotation) must
                # fail loudly, never bind outside its pin
                return FilterResult(
                    failed={
                        n: (
                            f"gang plan places {pod.key} on slice "
                            f"{planned_slice}, outside its slice-selector "
                            f"{sorted(pod.slice_selector)}"
                        )
                        for n in node_names
                    }
                )
            target = outcome.per_pod[pod.key].node
            failed = {n: f"gang plan places {pod.key} on {target}" for n in node_names if n != target}
            nodes = [n for n in node_names if n == target]
            if not nodes:
                # planned node isn't in the candidate list (e.g. cordoned):
                # drop the plan so the gang can re-plan elsewhere
                self.groups.drop_plan(self.groups.group_key(pod))
                return FilterResult(
                    failed={n: f"planned node {target} not in candidate list" for n in node_names}
                )
            return FilterResult(nodes=nodes, failed=failed)

        result = self._filter_plain(pod, plugin, node_names)
        if (
            not result.nodes
            and result.capacity_failure
            and plugin.name == "tpu"
            and self.active_preemption
        ):
            # preemption reasons in chip units; generic devices don't preempt
            if self._attempt_preemption(pod, self._slices_of(node_names)):
                result = self._filter_plain(pod, plugin, node_names)
        return result

    def _filter_plain(
        self, pod: PodInfo, plugin: DeviceSchedulerPlugin, node_names: List[str]
    ) -> FilterResult:
        views = self.cache.views()
        result = FilterResult()
        for name in node_names:
            node = self.cache.node(name)
            if node is None:
                result.failed[name] = "node not in scheduler cache"
                continue
            if pod.slice_selector is not None and (
                node.slice_id is None
                or node.slice_id not in pod.slice_selector
            ):
                # fail CLOSED: a slice-less node is outside every allowed
                # slice — placing a pinned pod there would violate the
                # tenant contract silently
                result.failed[name] = (
                    f"slice {node.slice_id} not in pod's slice-selector "
                    f"{sorted(pod.slice_selector)}"
                )
                continue
            view = views.get(node.slice_id) if node.slice_id else None
            fit = plugin.fit(node, pod, view)
            if fit.fits:
                result.nodes.append(name)
            else:
                result.failed[name] = fit.reason
                result.capacity_failure = result.capacity_failure or fit.capacity_failure
        return result

    def _slices_of(self, node_names: List[str]) -> set:
        """Slice ids reachable from a candidate node list — preemption must
        never evict on a slice the scheduler excluded."""
        out = set()
        for name in node_names:
            node = self.cache.node(name)
            if node is not None and node.slice_id:
                out.add(node.slice_id)
        return out

    # -- preemption -------------------------------------------------------
    def _members_for_preemption(self, pod: PodInfo) -> Optional[List[PodInfo]]:
        if not pod.pod_group:
            return [pod]
        # shared helper so the simulation can never diverge from what
        # try_plan would actually place
        return self.groups.planned_members(pod)

    def _attempt_preemption(self, pod: PodInfo, allowed_slices: set) -> bool:
        """Evict strictly-lower-priority units so `pod` (or its gang) fits.
        Returns True if anything was evicted (caller retries placement)."""
        if pod.priority <= 0 or not allowed_slices:
            return False
        members = self._members_for_preemption(pod)
        if members is None:
            return False
        layout, occupied = self._layout_occupancy(pod)
        allowed_slices = self._restrict_to_layout(pod, allowed_slices, layout)
        if not allowed_slices:
            return False
        decision, _ = self._find_victim_decision(
            pod, members, allowed_slices, layout, occupied
        )
        if decision is None or not decision.victims:
            return False
        uid_by_key = {
            k: u.uids.get(k) for u in decision.victims for k in u.pod_keys
        }
        for u in decision.victims:
            if u.unit_id.startswith("gang:"):
                self.groups.drop_plan(u.unit_id[len("gang:"):])
        evicted = 0
        for key in decision.victim_pod_keys():
            self._evict_pod(
                key,
                reason="Preempted",
                message=(
                    f"preempted by higher-priority {pod.key} "
                    f"(priority {pod.priority})"
                ),
                uid=uid_by_key.get(key),
            )
            evicted += 1
        self.metrics.inc("kubegpu_preemptions_total")
        self.metrics.inc("kubegpu_preempted_pods_total", evicted)
        log.warning(
            "preempted %d pods (units: %s) for %s (priority %d)",
            evicted,
            [u.unit_id for u in decision.victims],
            pod.key,
            pod.priority,
        )
        return True

    def _layout_occupancy(self, pod: PodInfo):
        """The gang's anchored-refit inputs, computed ONCE per preemption
        attempt and outside any lock (it LISTs pods)."""
        if not pod.pod_group:
            return {}, {}
        return self.groups.layout_and_occupancy_of(pod)

    def _restrict_to_layout(self, pod: PodInfo, allowed: Optional[set],
                            layout: Dict[str, int]):
        """Align eviction simulation with anchored re-planning: a
        partially-bound gang can only use its existing slice layout
        (podgroup.fit_gang_into_layout), so victims elsewhere would die for
        zero benefit.  The search is restricted to the layout's slices —
        one or many; multi-slice layouts take the joint cross-slice victim
        search in _find_victim_decision."""
        if pod.slice_selector is not None:
            allowed = (
                set(pod.slice_selector)
                if allowed is None
                else allowed & pod.slice_selector
            )
        if not layout:
            return allowed
        if allowed is None:
            return set(layout)
        return allowed & set(layout)

    def _find_victim_decision(self, pod: PodInfo, members, allowed,
                              layout, occupied):
        """Victim search shaped to how the gang would actually re-place:

        - anchored MULTI-slice gang → joint cross-slice search judged by
          the same fit_gang_into_layout call try_plan will make;
        - everything else → the per-slice find_victims;
        - fresh multislice-opted gang that no single slice can host even
          with eviction → joint search judged by fit_gang_multislice.

        Returns (decision, assignments snapshot) from ONE cache-lock
        acquisition so callers map victims to nodes from the same state
        the decision was computed against."""
        from kubegpu_tpu.grpalloc.multislice import (
            fit_gang_into_layout,
            fit_gang_multislice,
        )
        from kubegpu_tpu.scheduler.preemption import find_victims_joint

        pods_raw = self.api.list_pods()
        selector = pod.slice_selector
        with self.cache.lock:
            assignments = self.cache.assignments_snapshot()
            units = self._shield_fresh(collect_units(pods_raw, assignments))
            views = self.cache.views()
            if len(layout) > 1:
                def fits_layout(trial):
                    # mirror try_plan exactly: views are filtered by the
                    # pod's slice selector BEFORE the anchored refit, so a
                    # recreated member whose selector excludes a layout
                    # slice fails here too — never evict for a re-plan
                    # that is guaranteed to refuse
                    tv = {
                        sid: v
                        for sid, v in trial.items()
                        if selector is None or sid in selector
                    }
                    return fit_gang_into_layout(
                        tv, members, layout, occupied
                    ).success

                return (
                    find_victims_joint(
                        views, units, pod.priority, fits_layout, allowed
                    ),
                    assignments,
                )
            decision = find_victims(views, units, members, pod.priority, allowed)
            if decision is not None:
                return decision, assignments
            if pod.allow_multislice and not layout:
                def fits_ms(trial):
                    tv = {
                        sid: v
                        for sid, v in trial.items()
                        if allowed is None or sid in allowed
                    }
                    return fit_gang_multislice(
                        tv, members, allow_multislice=True
                    ).success

                return (
                    find_victims_joint(
                        views, units, pod.priority, fits_ms, allowed
                    ),
                    assignments,
                )
            return None, assignments

    def _shield_fresh(self, units):
        """Anti-starvation: drop units still inside their min-runtime
        window from the victim candidate set (both preemption modes flow
        through _find_victim_decision, so active filter-evictions and the
        advisory /preemption verb honor the same shield)."""
        if self.preemption_min_runtime_s <= 0:
            return units
        now = time.time()
        out = []
        for u in units:
            age = now - u.last_bound_at
            if u.last_bound_at and age < self.preemption_min_runtime_s:
                log.info(
                    "unit %s shielded from preemption (admitted %.0fs ago, "
                    "min runtime %.0fs)",
                    u.unit_id, age, self.preemption_min_runtime_s,
                )
                continue
            out.append(u)
        return out

    def preemption_victims(
        self, pod_obj: dict, candidate_nodes: Optional[List[str]] = None
    ) -> Dict[str, List[str]]:
        """The extender /preemption verb (advisory: kube-scheduler performs
        the eviction): node name -> victim pod keys on that node, restricted
        to the nodes kube-scheduler nominated (when provided)."""
        try:
            pod = annotations.pod_from_k8s(pod_obj)
        except Exception:  # noqa: BLE001
            return {}
        members = self._members_for_preemption(pod)
        if members is None:
            return {}
        allowed = (
            self._slices_of(candidate_nodes) if candidate_nodes is not None else None
        )
        if candidate_nodes is not None and not allowed:
            return {}
        layout, occupied = self._layout_occupancy(pod)
        allowed = self._restrict_to_layout(pod, allowed, layout)
        if allowed is not None and not allowed:
            return {}
        decision, assignments = self._find_victim_decision(
            pod, members, allowed, layout, occupied
        )
        if decision is None:
            return {}
        by_node: Dict[str, List[str]] = {}
        for key in decision.victim_pod_keys():
            a = assignments.get(key)
            if a is None:
                continue
            # victims are restricted to candidate slices above, but NOT
            # filtered per candidate node: gangs are evicted whole, and a
            # gang's members may sit on sibling (non-nominated) nodes of
            # the same slice
            by_node.setdefault(a.node, []).append(key)
        return by_node

    # -- prioritize -------------------------------------------------------
    def prioritize(self, pod_obj: dict, node_names: List[str]) -> List[Tuple[str, int]]:
        """(host, score 0-10) per extender API."""
        t0 = time.monotonic()
        try:
            pod = annotations.pod_from_k8s(pod_obj)
        except Exception:  # noqa: BLE001
            return [(n, 0) for n in node_names]
        try:
            plugin, _ = self._owning_plugin(pod)
            if plugin is None:  # no device request, or mixed-type error
                return [(n, 0) for n in node_names]
            if self._is_tpu_gang(pod):
                plan = self.groups.plan_for(pod)
                target = plan.per_pod[pod.key].node if plan else None
                return [(n, 10 if n == target else 0) for n in node_names]
            views = self.cache.views()
            raw = []
            for name in node_names:
                node = self.cache.node(name)
                if node is None:
                    raw.append((name, None))
                    continue
                view = views.get(node.slice_id) if node.slice_id else None
                fit = plugin.fit(node, pod, view)
                raw.append((name, fit.score if fit.fits else None))
            return _scale_scores(raw)
        finally:
            self.metrics.inc("kubegpu_prioritize_total")
            self.metrics.observe("kubegpu_prioritize_seconds", time.monotonic() - t0)

    # -- bind -------------------------------------------------------------
    def bind(self, namespace: str, name: str, node_name: str) -> Optional[str]:
        """Commit placement; returns an error string or None on success."""
        t0 = time.monotonic()
        try:
            return self._bind(namespace, name, node_name)
        finally:
            self.metrics.inc("kubegpu_bind_total")
            self.metrics.observe("kubegpu_bind_seconds", time.monotonic() - t0)

    def _bind(self, namespace: str, name: str, node_name: str) -> Optional[str]:
        key = f"{namespace}/{name}"
        try:
            pod_obj = self.api.get_pod(namespace, name)
        except NotFound:
            return f"pod {key} not found"
        try:
            pod = annotations.pod_from_k8s(pod_obj)
        except Exception as e:  # noqa: BLE001
            return f"unparseable pod {key}: {e}"
        plugin, plugin_err = self._owning_plugin(pod)
        if plugin_err:
            return f"cannot bind {key}: {plugin_err}"

        assignment: Optional[Assignment] = None
        reserved_here = False
        gk = self.groups.group_key(pod)
        is_tpu_gang = self._is_tpu_gang(pod) and gk is not None

        # Gang pods are marked mid-bind for the WHOLE verb — from before
        # the reservation check through the durable commit: a concurrent
        # drop_plan (reconcile, sibling's bind failure) must not forget a
        # reservation this bind is about to rely on (TOCTOU), and an
        # unexpected exception ANYWHERE in between must not leave the key
        # marked forever (which would shield its reservation from
        # drop_plan/expiry and leak its chips until GET-confirmed
        # divergence).  Hence one try/finally around everything from the
        # first mark.
        if is_tpu_gang:
            self.groups.mark_binding(key)
        try:
            if plugin is None:
                assignment = None  # plain bind, no device commitment
            elif is_tpu_gang:
                plan = self.groups.plan_for(pod)
                if plan is not None and pod.key in plan.per_pod:
                    assignment = plan.per_pod[pod.key]
                else:
                    # plan may have been dropped (fully committed) while the
                    # scheduler retries this bind: fall back to the live
                    # reservation
                    assignment = self.cache.assignment_of(key)
                    if assignment is None:
                        return f"gang pod {key} has no live plan (re-run filter)"
                if assignment.node != node_name:
                    # Mid-bind marking cuts both ways: a drop_plan/expiry
                    # racing this verb skipped the marked key when it freed
                    # the plan's other reservations.  If the plan is gone
                    # NOW and the reservation is still merely assumed,
                    # nothing else will ever free it (no plan owner, no
                    # TTL) — forget it before erroring out, or the chips
                    # stay charged until GET-confirmed divergence.  A
                    # still-live plan keeps ownership: its expiry/drop
                    # frees the key once the finally below unmarks it.
                    if (
                        self.groups.plan_for(pod) is None
                        and key in self.cache.assumed_keys()
                    ):
                        self.cache.forget(key)
                    return (
                        f"gang plan places {key} on {assignment.node}, "
                        f"but bind requested {node_name}"
                    )
                # The plan's reservation can be GONE by now: a chip-death
                # eviction of this (unbound) member released it between
                # planning and bind.  Annotating without a live charge
                # writes a durable claim on chips another pod may
                # legitimately take — double-allocation (found by the
                # gang-churn chaos soak).  Re-acquire or refuse.  (The key
                # is already marked mid-bind, so a drop_plan landing
                # between this check and the commit cannot forget the
                # reservation.)
                reacquire_err = None
                with self.cache.lock:
                    if self.cache.assignment_of(key) is None:
                        try:
                            self.cache.assume(key, assignment)
                            reserved_here = True
                        except (ValueError, KeyError) as e:
                            reacquire_err = e
                if reacquire_err is not None:
                    self.metrics.inc("kubegpu_bind_conflicts_total")
                    # the plan is UNEXECUTABLE — its chips are durably held
                    # elsewhere.  Drop it now: a live plan shields the gang
                    # from both re-planning and the stranded sweep, so
                    # keeping it would wedge the gang until plan-TTL expiry
                    # (found by the chaos soak).  Called OUTSIDE the cache
                    # lock: drop_plan takes groups-lock-then-cache-lock,
                    # and taking it under the cache lock would be the
                    # reverse order of every other path (ABBA deadlock).
                    # The finally below unmarks mid-bind AFTER this drop,
                    # so the drop itself cannot free the reservation — the
                    # planless-forget in the commit-failure path never
                    # applies here (nothing was durably written).
                    self.groups.drop_plan(gk)
                    return (
                        f"gang reservation for {key} was released and "
                        f"cannot be reacquired (plan dropped, re-run "
                        f"filter): {reacquire_err}"
                    )
            else:
                with self.cache.lock:
                    node = self.cache.node(node_name)
                    if node is None:
                        return f"unknown node {node_name}"
                    view = self.cache.views().get(node.slice_id) if node.slice_id else None
                    fit = plugin.fit(node, pod, view)
                    if not fit.fits:
                        self.metrics.inc("kubegpu_bind_conflicts_total")
                        return f"no longer fits on {node_name}: {fit.reason}"
                    assignment = fit.assignment
                    try:
                        self.cache.assume(key, assignment)
                        reserved_here = True
                    except (ValueError, KeyError) as e:
                        self.metrics.inc("kubegpu_bind_conflicts_total")
                        return f"reservation race on {node_name}: {e}"

            # durable commit: assignment annotation first, then the
            # binding — a crash between the two leaves an annotated-unbound
            # pod that refresh() replays correctly (state lives in the API
            # server).
            if self.serving_gate is not None and not self.serving_gate():
                # leadership lapsed since the HTTP gate: a promoted
                # standby may already own these chips — abort BEFORE
                # anything durable is written and roll the local
                # reservation back (nothing to clear in the API server)
                if reserved_here or (
                    is_tpu_gang and self.groups.plan_for(pod) is None
                ):
                    self.cache.forget(key)
                self.metrics.inc("kubegpu_bind_conflicts_total")
                return (
                    f"lost leadership before committing {key}; bind "
                    "aborted (kube-scheduler retries against the leader)"
                )
            try:
                if assignment is not None:
                    # stamped on the SHARED object (plan/cache hold the
                    # same one) so cache and annotation agree; drives the
                    # preemption min-runtime shield across restarts
                    assignment.bound_at = time.time()
                    self.api.patch_pod_annotations(
                        namespace,
                        name,
                        {annotations.POD_ASSIGNMENT: annotations.encode_assignment(assignment)},
                    )
                self.api.bind_pod(namespace, name, node_name)
            except (Conflict, NotFound, OSError) as e:
                if reserved_here:
                    self.cache.forget(key)
                elif is_tpu_gang and self.groups.plan_for(pod) is None:
                    # the plan vanished mid-bind (dropped/expired): this
                    # reservation has no owner left to expire it — forget,
                    # or the chips leak until a GET-confirmed divergence
                    self.cache.forget(key)
                if assignment is not None:
                    # clear the annotation for gang pods too: leaving it
                    # would let a later refresh() replay a ghost placement
                    # for a pod that never bound (stranding its chips)
                    try:
                        self.api.patch_pod_annotations(
                            namespace, name, {annotations.POD_ASSIGNMENT: ""}
                        )
                    except Exception:  # noqa: BLE001
                        pass
                return f"bind of {key} to {node_name} failed: {e}"

            if assignment is not None:
                # annotation + binding both durable: refresh() now rebuilds
                # this reservation from the API server
                self.cache.confirm(key)
            if is_tpu_gang:
                self.groups.mark_committed(key, gk)
        finally:
            if is_tpu_gang:
                self.groups.unmark_binding(key)
        if assignment is not None:
            self._record_placement_metrics(assignment)
            chips = assignment.all_chips()
            if chips:
                self.events.pod_event(
                    namespace, name, "DeviceAssigned",
                    (
                        f"assigned {len(chips)} TPU chip(s) on {node_name} "
                        f"(slice {assignment.slice_id}, coords "
                        f"{sorted(c.coords for c in chips)})"
                    ),
                    uid=pod.uid,
                )
        log.info("bound %s -> %s", key, node_name)
        return None

    def _record_placement_metrics(self, a: Assignment) -> None:
        chips = a.all_chips()
        if not chips:
            if a.grouped:
                self.metrics.inc("kubegpu_placements_total")
                self.metrics.inc(
                    "kubegpu_grouped_allocated_total",
                    sum(a.grouped_totals().values()),
                )
            return
        node = self.cache.node(a.node)
        contiguous = False
        if node is not None and node.mesh_shape is not None:
            coords = {c.coords for c in chips}
            wrap = node.wrap or tuple(False for _ in node.mesh_shape)
            contiguous = is_contiguous_submesh(coords, node.mesh_shape, wrap)
        self.metrics.inc("kubegpu_placements_total")
        if contiguous:
            self.metrics.inc("kubegpu_placements_contiguous_total")
        self.metrics.inc("kubegpu_chips_allocated_total", len(chips))

    # -- lifecycle events -------------------------------------------------
    def resync(self) -> None:
        """Periodic resync (ExtenderServer loop): reconcile the cache with
        the API server, then sweep for assignments referencing died chips —
        the consistency backstop behind the node watch (and the only
        failure detector when the API server offers no watch).  One
        snapshot indexed by host keeps the sweep O(assignments), not
        O(nodes x assignments).

        The refresh AND the sweep's LISTs run OUTSIDE the lifecycle lock:
        they are network I/O, and holding the lock across them would stall
        the node-watch fast path — the very evictions the watch exists to
        accelerate — behind API-server round-trips.  refresh() has its own
        locking and tolerates concurrent watch updates."""
        self.cache.refresh()
        nodes_raw = self.api.list_nodes()
        pods_raw = self.api.list_pods()
        # plan reconciliation (missed-DELETED backstop): network GETs, so
        # outside the lifecycle lock like the other I/O here
        self.groups.reconcile(
            {
                f"{(o.get('metadata') or {}).get('namespace', 'default')}/"
                f"{(o.get('metadata') or {}).get('name', '')}"
                for o in pods_raw
            },
            self.api.get_pod,
        )
        with self._lifecycle_lock:
            self._resync_locked(nodes_raw)
            self._sweep_stranded_gangs(pods_raw)

    def _resync_locked(self, nodes_raw: List[dict]) -> None:
        if self.evict_on_chip_failure:
            self._sweep_chip_health(nodes_raw)
        # The conflict sweep runs REGARDLESS of evict_on_chip_failure —
        # durable double-annotation is an accounting pathology, not a
        # chip-health event (same reasoning as the stranded-gang sweep).
        conflicted = self.cache.conflicted_assignments()
        self._conflict_strikes = {
            k: v for k, v in self._conflict_strikes.items() if k in conflicted
        }
        for key in sorted(conflicted):
            strikes = self._conflict_strikes.get(key, 0) + 1
            self._conflict_strikes[key] = strikes
            if strikes < self.absent_grace:
                continue
            del self._conflict_strikes[key]
            self._drop_gang_plan_of(key)
            self._evict_pod(
                key,
                reason="AssignmentConflict",
                message=(
                    "annotated chips are held by another assignment "
                    f"({strikes} consecutive resyncs): durable double-"
                    "annotation resolved toward the charged owner"
                ),
            )
            self.metrics.inc("kubegpu_health_evictions_total")
            log.warning(
                "evicted %s: its annotated chips are held by another "
                "assignment (%d consecutive resyncs) — durable "
                "double-annotation resolved toward the charged owner",
                key, strikes,
            )

    def _sweep_chip_health(self, nodes_raw: List[dict]) -> None:
        by_host: Dict[str, list] = {}
        for key, a in self.cache.assignments_snapshot().items():
            for r in a.all_chips():
                by_host.setdefault(r.host, []).append((key, r))
        # prune strike entries whose assignment is gone, so the maps stay
        # O(live assignments) across a long-lived server
        valid = {
            (key, r.host, r.device_index)
            for host_refs in by_host.values()
            for key, r in host_refs
        }
        self._absent_chip_strikes = {
            k: v for k, v in self._absent_chip_strikes.items() if k in valid
        }
        live = {(obj.get("metadata") or {}).get("name", "") for obj in nodes_raw}
        for obj in nodes_raw:
            name = (obj.get("metadata") or {}).get("name", "")
            if name in by_host:
                self._evict_on_dead_chips(obj, by_host[name])
        # Total-failure mode the per-node sweep can never see: the node (or
        # its advertiser) is GONE from the LIST entirely, so no future
        # advertisement ever re-marks its chips unhealthy and its gangs
        # would stay wedged forever.  refresh() records such pods as
        # orphaned; evict them after the same grace window, since a node
        # can blip out of one LIST.  A node that IS listed but whose
        # topology annotation failed to decode also orphans its pods in the
        # cache — that is version skew / corruption, not node loss, and
        # must stay a warning, never an eviction.
        orphans = {
            key: host
            for key, host in self.cache.orphaned_assignments().items()
            if host not in live
        }
        self._missing_node_strikes = {
            k: v
            for k, v in self._missing_node_strikes.items()
            if orphans.get(k[0]) == k[1]
        }
        for key, host in sorted(orphans.items()):
            strikes = self._missing_node_strikes.get((key, host), 0) + 1
            self._missing_node_strikes[(key, host)] = strikes
            if strikes < self.absent_grace:
                continue
            del self._missing_node_strikes[(key, host)]
            self._drop_gang_plan_of(key)
            self._evict_pod(
                key,
                reason="NodeLost",
                message=(
                    f"node {host} is no longer advertised "
                    f"({strikes} consecutive resyncs)"
                ),
            )
            self.metrics.inc("kubegpu_health_evictions_total")
            log.warning(
                "evicted %s: its node %s is no longer advertised "
                "(%d consecutive resyncs)",
                key, host, strikes,
            )
    def _sweep_stranded_gangs(self, pods_raw: List[dict]) -> None:
        """Incomplete-gang rollback (all-or-nothing applies to the
        admission OUTCOME, not just planning): a gang that keeps SOME
        members bound but not all for `stranded_grace` consecutive resyncs
        WITHOUT PROGRESS is leaking capacity — mid-admission disruption
        (chip death, preemption of a sibling) stranded it, and other
        tenants may have taken the chips its pending members need,
        possibly forever.  Roll the bound members back; the controller
        recreates them and the whole gang re-admits atomically when
        capacity allows.

        Strikes count only STALLED partiality: they reset whenever the
        bound set changes (admission converging, replacements landing)
        and never accrue while a live plan covers the gang (members are
        actively binding).  Runs regardless of evict_on_chip_failure —
        capacity-leak rollback is not a chip-health feature.

        Because rollback deletes running pods, the partiality verdict is
        hardened against three ways a HEALTHY gang can look partial:

        - **Succeeded members** (e.g. garbage-collected one at a time by a
          TTL controller): their work is done and no replacement is owed,
          so they leave the bound set AND shrink the denominator —
          including after GC deletes them (the sweep remembers every
          member it ever saw Succeeded, per `_gang_done`).  Their chips
          are already uncharged: the cache treats terminal-phase pods as
          holding nothing (ClusterCache.refresh).
        - **Terminating members**: they hold spec.nodeName but are
          leaving, so they never count as bound — the gang still owes a
          replacement, so the denominator keeps them.  Their stale
          assignment annotations are evicted along with a rollback so a
          rolled-back gang frees ALL its chips.  (Failed members are
          excluded from bound the same way; their chips are uncharged by
          the terminal-phase rule, no eviction needed.)
        - **Size disagreement** (a recreated member carrying a stale
          pod-group-size annotation): the denominator is the CONSENSUS of
          the members' declared sizes — most common wins, ties toward the
          smaller (the direction that avoids rolling back a healthy
          gang) — never last-write-wins."""
        gangs: Dict[str, Dict[str, list]] = {}
        for obj in pods_raw:
            try:
                p = annotations.pod_from_k8s(obj, strict=False)
            except Exception:  # noqa: BLE001
                continue
            if not p.pod_group or TpuRequest.from_pod(p).total_chips == 0:
                continue
            gk = f"{p.namespace}/{p.pod_group}"
            g = gangs.setdefault(
                gk, {"sizes": [], "bound": [], "releasable": [], "live": 0,
                     "uids": {}, "incarnations": []}
            )
            g["uids"][p.key] = p.uid
            if p.phase == "Succeeded":
                # remembered in the registry (shared with the planner, so
                # sweep denominator and re-plan requirement never diverge)
                # because a TTL controller may GC the pod before the next
                # resync — and a vanished Succeeded member must KEEP
                # shrinking the denominator.  Scoped to the member's own
                # incarnation: an old run's completions never shrink a new
                # run's denominator.
                self.groups.note_done(gk, p.key, p.pod_group_uid)
                continue
            # a name reused by a live recreation must not double-count
            # (once as bound, once as remembered-done)
            self.groups.note_live(gk, p.key)
            g["sizes"].append(p.pod_group_size)
            if p.terminal or p.terminating:
                if p.terminating and not p.terminal and (
                    annotations.assignment_from_pod(p.annotations) is not None
                ):
                    g["releasable"].append(p.key)
                continue
            g["live"] += 1
            g["incarnations"].append(p.pod_group_uid)
            if p.node_name:
                g["bound"].append(p.key)
        # forget completed-member memory for gangs no longer listed at all
        # (fully GC'd): nothing is left to judge, and a later gang reusing
        # the name must start clean
        self.groups.prune_done(gangs)
        self._suspect_warned &= set(gangs)
        stranded = {}
        outstanding = {}
        for gk, g in gangs.items():
            # shared formula with the planner (gang_arithmetic), judged
            # against the LIVE members' incarnation (consensus, computed
            # inside gang_arithmetic like the planner's): an old run's
            # remembered completions must not shrink a new run's denominator
            size, suspect = self.groups.gang_arithmetic(
                gk, _consensus_size(g["sizes"]), g["live"], g["incarnations"]
            )
            if suspect:
                # over-subscribed arithmetic (gang name reused without
                # pod-group-uid, or stray extra members): whose gang the
                # bound members belong to is ambiguous, and rollback
                # DELETES running pods — decline to judge.  The planner's
                # full-size fallback still lets the new run form; worst
                # case is a capacity leak an operator can see, never a
                # healthy gang destroyed.  Warned once per episode — the
                # condition persists across resyncs.
                if gk not in self._suspect_warned:
                    self._suspect_warned.add(gk)
                    log.warning(
                        "gang %s: completed-member arithmetic over-"
                        "subscribed (name reused without %s?); skipping "
                        "stranded-gang judgment while it persists",
                        gk, annotations.POD_GROUP_UID,
                    )
                continue
            self._suspect_warned.discard(gk)
            if 0 < len(g["bound"]) < size:
                stranded[gk] = tuple(sorted(g["bound"]))
                outstanding[gk] = size
        self._stranded_strikes = {
            k: v for k, v in self._stranded_strikes.items() if k in stranded
        }
        for gk, bound in sorted(stranded.items()):
            if self.groups.has_live_plan(gk):
                self._stranded_strikes.pop(gk, None)
                continue  # actively binding: not stalled
            strikes, prev_bound = self._stranded_strikes.get(gk, (0, bound))
            if prev_bound != bound:
                strikes = 0  # progress (or churn): restart the window
            strikes += 1
            self._stranded_strikes[gk] = (strikes, bound)
            if strikes < self.stranded_grace:
                continue
            del self._stranded_strikes[gk]
            self.groups.drop_plan(gk)
            for key in (*bound, *sorted(gangs[gk]["releasable"])):
                self._evict_pod(
                    key,
                    reason="GangRollback",
                    message=(
                        f"gang {gk} stayed partially bound for {strikes} "
                        "resyncs without progress; rolling back so the "
                        "whole gang can re-admit atomically"
                    ),
                    uid=gangs[gk]["uids"].get(key),
                )
            self.metrics.inc("kubegpu_stranded_gang_rollbacks_total")
            log.warning(
                "rolled back incomplete gang %s (%d bound of %d outstanding "
                "for %d consecutive resyncs without progress): freeing its "
                "chips so the whole gang can re-admit atomically",
                gk, len(bound), outstanding[gk], strikes,
            )

    def on_pod_deleted(self, pod_obj: dict) -> None:
        # lenient parse, like every LIST-path consumer: a malformed device
        # quantity must not make a DELETED event invisible — dropping it
        # would leak the pod's chips and keep its gang plan live until the
        # next resync, exactly the wait the pod watch exists to remove
        try:
            pod = annotations.pod_from_k8s(pod_obj, strict=False)
        except Exception:  # noqa: BLE001
            return
        # Stale-event guard: the watch delivers by NAME, and a controller
        # can recreate the name before a delayed DELETED event drains.
        # Acting on it then would free the RECREATED pod's live
        # reservation (double-allocation).  Only a fresh NotFound proves
        # the name is really gone; on existence or transient error, skip —
        # chips merely look used one resync longer (the safe direction,
        # same discipline as the cache's GET-confirmed reconciliation).
        try:
            self.api.get_pod(pod.namespace, pod.name)
            return  # exists (recreated or not-yet-deleted): stale event
        except NotFound:
            pass
        except Exception:  # noqa: BLE001 - transient: resync will converge
            return
        self.cache.remove_pod(pod.key)
        self.groups.on_pod_deleted(pod)

    def on_node_updated(self, node_obj: dict) -> None:
        with self._lifecycle_lock:
            self.cache.update_node(node_obj)
            if self.evict_on_chip_failure:
                self._evict_on_dead_chips(node_obj)

    def _evict_pod(
        self,
        key: str,
        reason: str = "Evicted",
        message: str = "",
        uid: Optional[str] = None,
    ) -> None:
        """The one eviction sequence (preemption AND health eviction):
        clear the assignment annotation BEFORE deleting — a victim
        lingering in Terminating (graceful deletion on a real cluster)
        must not be replayed by the next cache refresh onto chips a new
        placement may own — then delete and release the cache entry.
        The Warning Event (kubectl describe matches it by
        involvedObject.uid) is emitted AFTER the delete call so it records
        what actually happened — a failed or moot delete must not leave a
        record claiming the pod was evicted.  Callers that already hold
        the uid (preemption victims, sweep-parsed pods) thread it in;
        otherwise one GET fetches it before the pod vanishes (evictions
        are rare)."""
        ns, name = key.split("/", 1)
        if uid is None:
            try:
                uid = (self.api.get_pod(ns, name).get("metadata") or {}).get(
                    "uid", ""
                )
            except Exception:  # noqa: BLE001 - already gone / transient
                uid = ""
        try:
            self.api.patch_pod_annotations(
                ns, name, {annotations.POD_ASSIGNMENT: ""}
            )
        except (NotFound, OSError):
            pass
        deleted = True
        try:
            self.api.delete_pod(ns, name)
        except NotFound:
            deleted = False  # already gone: nothing evicted, no record owed
        self.cache.remove_pod(key)
        if deleted:
            self.events.pod_event(
                ns, name, reason,
                message or "evicted by kubegpu-tpu-scheduler",
                type_="Warning", uid=uid,
            )

    def _evict_on_dead_chips(self, node_obj: dict, host_refs=None) -> None:
        """Failure detection → elastic recovery (SURVEY.md §5.3): when the
        advertiser reports a chip unhealthy (or gone), the pods holding it
        are dead weight — their process lost its device and cannot recover
        in place.  Evict them (controller recreates; the replacement
        re-schedules onto healthy chips, gang members anchored to their
        gang's existing slice layout by the re-plan path).  Healthy
        siblings on other chips are untouched."""
        try:
            node = annotations.node_from_k8s(node_obj)
        except Exception:  # noqa: BLE001
            return
        if node.slice_id is None:
            return
        if host_refs is None:
            host_refs = [
                (key, r)
                for key, a in self.cache.assignments_snapshot().items()
                for r in a.all_chips()
                if r.host == node.name
            ]
        present = {ch.device_index for ch in node.chips}
        dead = {ch.device_index for ch in node.chips if not ch.healthy}
        # strikes count distinct ADVERTISEMENTS showing the chip absent, not
        # observations: the resync loop re-reading one stale truncated
        # annotation (or a watch event plus the next resync double-counting
        # the same write) must not accumulate toward eviction.  The
        # advertiser bumps NODE_ADVERT_SEQ every cycle; fall back to the
        # topology payload itself for third-party advertisers.
        ann = (node_obj.get("metadata") or {}).get("annotations") or {}
        fingerprint = ann.get(
            annotations.NODE_ADVERT_SEQ, ann.get(annotations.NODE_TOPOLOGY, "")
        )
        victim_set = set()
        for key, r in host_refs:
            strike_key = (key, node.name, r.device_index)
            if r.device_index in dead:
                # explicit Unhealthy report: positive signal, evict now
                victim_set.add(key)
                self._absent_chip_strikes.pop(strike_key, None)
            elif r.device_index not in present:
                # absence is ambiguous (advertiser restart, truncated
                # enumeration): require absent_grace distinct advertisements
                strikes, last_fp = self._absent_chip_strikes.get(
                    strike_key, (0, None)
                )
                if last_fp != fingerprint:
                    strikes += 1
                    self._absent_chip_strikes[strike_key] = (strikes, fingerprint)
                if strikes >= self.absent_grace:
                    victim_set.add(key)
                    del self._absent_chip_strikes[strike_key]
            else:
                self._absent_chip_strikes.pop(strike_key, None)
        victims = sorted(victim_set)
        for key in victims:
            # invalidate the victim's live gang plan FIRST: a stale plan
            # would rebind the recreated member onto the exact dead chip,
            # producing an endless evict/recreate/rebind loop
            self._drop_gang_plan_of(key)
            self._evict_pod(
                key,
                reason="ChipFailure",
                message=(
                    f"assigned TPU chip(s) on {node.name} died "
                    f"(dead device indices: {sorted(dead)}); controller "
                    "recreates onto healthy chips"
                ),
            )
            self.metrics.inc("kubegpu_health_evictions_total")
            log.warning(
                "evicted %s: its chip(s) on %s died (dead=%s)",
                key, node.name, sorted(dead),
            )

    def _drop_gang_plan_of(self, key: str) -> None:
        ns, name = key.split("/", 1)
        try:
            pod = annotations.pod_from_k8s(self.api.get_pod(ns, name), strict=False)
        except Exception:  # noqa: BLE001 - pod already gone: nothing to drop
            return
        if pod.pod_group:
            self.groups.drop_plan(f"{ns}/{pod.pod_group}")
