"""Scheduler-extender HTTP service (SURVEY.md §2 #4, §3.1).

Implements the Kubernetes scheduler-extender wire API — POST /filter,
/prioritize, /bind with the upstream JSON shapes — plus /healthz, /metrics
(Prometheus text) and /state (debug dump), mirroring the observability the
rebuild adds over the reference (SURVEY.md §5.1/§5.5).

Run in-cluster against the real API server:
    python -m kubegpu_tpu.scheduler.server --listen 0.0.0.0:12345

or self-hosted on a fabricated cluster for demos/tests (no k8s needed):
    python -m kubegpu_tpu.scheduler.server --fake-cluster v5e-16

Register with kube-scheduler via deploy/extender-policy.json.
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from kubegpu_tpu.scheduler.core import Scheduler
from kubegpu_tpu.utils.apiserver import ApiServer, InMemoryApiServer

log = logging.getLogger(__name__)


def make_handler(sched: Scheduler, is_leader=None, auth_token=None):
    """is_leader: optional callable gating the scheduling verbs (leader
    election).  A standby replica answers them 503 "not leader" — a
    NON-FATAL refusal kube-scheduler treats as an extender error and
    retries, by which time the real leader (or this replica, newly
    promoted) answers.  Health/metrics/state stay served on standbys:
    probes and operators still need them.

    auth_token: optional bearer token required on the PRIVILEGED verbs —
    /bind commits placements and /preemption nominates deletions; with
    the default in-cluster network they would otherwise be callable by
    any pod that can reach the Service.  Filter/prioritize stay open
    (read-only advice; kube-scheduler is the only caller that matters and
    gating them would take scheduling down on token skew)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # -- plumbing ----------------------------------------------------
        def setup(self):
            # TLS: the listening socket is wrapped with
            # do_handshake_on_connect=False, so the handshake happens
            # HERE, on this connection's own thread, under a deadline —
            # a stalled or silent client costs one worker thread for 10 s,
            # never the accept loop (which would take down every verb and
            # the health probes with it)
            if hasattr(self.request, "do_handshake"):
                prev = self.request.gettimeout()
                self.request.settimeout(10.0)
                try:
                    self.request.do_handshake()
                finally:
                    self.request.settimeout(prev)
            super().setup()
        def _read_json(self) -> Optional[dict]:
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                return json.loads(raw) if raw else {}
            except (ValueError, json.JSONDecodeError):
                return None

        def _send(self, code: int, payload, content_type="application/json") -> None:
            body = (
                json.dumps(payload).encode()
                if content_type == "application/json"
                else payload.encode()
            )
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # route through logging, not stderr
            log.debug("http: " + fmt, *args)

        # -- verbs -------------------------------------------------------
        def do_DELETE(self):
            parts = self.path.strip("/").split("/")
            if (
                len(parts) == 3
                and parts[0] == "pods"
                and isinstance(sched.api, InMemoryApiServer)
            ):
                ns, name = parts[1], parts[2]
                try:
                    obj = sched.api.get_pod(ns, name)
                    sched.api.delete_pod(ns, name)
                    sched.on_pod_deleted(obj)
                    self._send(200, {"deleted": f"{ns}/{name}"})
                except Exception as e:  # noqa: BLE001
                    self._send(404, {"error": str(e)})
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_GET(self):
            if self.path == "/healthz":
                # liveness: the process serves — true on standbys too
                self._send(200, "ok", content_type="text/plain")
            elif self.path == "/readyz":
                # readiness: ONLY the leader belongs in the Service's
                # Endpoints — a Ready standby would eat ~1/replicas of all
                # extender calls with 503s in steady state, failing those
                # scheduling cycles permanently, not just during failover
                if is_leader is None or is_leader():
                    self._send(200, "ok", content_type="text/plain")
                else:
                    self._send(503, "standby", content_type="text/plain")
            elif self.path == "/metrics":
                self._send(200, sched.metrics.render(), content_type="text/plain")
            elif self.path == "/state":
                state = _debug_state(sched)
                state["is_leader"] = is_leader() if is_leader else True
                self._send(200, state)
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            body = self._read_json()
            if body is None:
                self._send(400, {"Error": "malformed JSON body"})
                return
            if (
                is_leader is not None
                and not is_leader()
                and self.path in ("/filter", "/prioritize", "/bind", "/preemption")
            ):
                # the in-memory cache is only authoritative on the leader;
                # a standby answering verbs would assume chips the leader
                # knows nothing about (double-allocation)
                self._send(503, {"Error": "not leader (standby replica)"})
                return
            if auth_token and self.path in ("/bind", "/preemption"):
                import hmac

                sent = self.headers.get("Authorization", "")
                # constant-time compare: the token gates exactly the
                # callers a timing oracle would serve
                if not hmac.compare_digest(sent, f"Bearer {auth_token}"):
                    self._send(401, {"Error": "unauthorized (bearer token required)"})
                    return
            try:
                if self.path == "/filter":
                    self._send(200, self._filter(body))
                elif self.path == "/prioritize":
                    self._send(200, self._prioritize(body))
                elif self.path == "/bind":
                    self._send(200, self._bind(body))
                elif self.path == "/preemption":
                    self._send(200, self._preemption(body))
                elif self.path == "/pods" and isinstance(sched.api, InMemoryApiServer):
                    # fake-cluster demo mode only: lets curl drive the full
                    # filter→prioritize→bind flow without a real API server
                    self._send(200, sched.api.create_pod(body))
                else:
                    self._send(404, {"error": f"no route {self.path}"})
            except Exception as e:  # noqa: BLE001 - extender must never hang kube-scheduler
                log.exception("handler error on %s", self.path)
                self._send(200, {"Error": f"internal error: {e}"})

        def _candidates(self, body: dict) -> Tuple[list, bool]:
            """Extender args carry either NodeNames (preferred) or full
            Nodes.Items; return (names, used_full_objects)."""
            if body.get("NodeNames"):
                return list(body["NodeNames"]), False
            items = (body.get("Nodes") or {}).get("Items") or []
            return [n.get("metadata", {}).get("name", "") for n in items], True

        def _filter(self, body: dict) -> dict:
            names, full = self._candidates(body)
            result = sched.filter(body.get("Pod") or {}, names)
            out = {
                "NodeNames": result.nodes,
                "FailedNodes": result.failed,
                "Error": result.error,
            }
            if full:
                items = (body.get("Nodes") or {}).get("Items") or []
                keep = set(result.nodes)
                out["Nodes"] = {
                    "Items": [
                        n for n in items if n.get("metadata", {}).get("name") in keep
                    ]
                }
            return out

        def _prioritize(self, body: dict) -> list:
            names, _ = self._candidates(body)
            scores = sched.prioritize(body.get("Pod") or {}, names)
            return [{"Host": h, "Score": s} for h, s in scores]

        def _bind(self, body: dict) -> dict:
            err = sched.bind(
                body.get("PodNamespace", "default"),
                body.get("PodName", ""),
                body.get("Node", ""),
            )
            return {"Error": err or ""}

        def _preemption(self, body: dict) -> dict:
            """ExtenderPreemptionArgs -> NodeNameToMetaVictims (advisory:
            kube-scheduler performs the eviction for this verb).  The
            nominated-node map scopes which slices victims may come from."""
            nominated = (
                body.get("NodeNameToVictims") or body.get("NodeNameToMetaVictims") or {}
            )
            candidates = sorted(nominated) if nominated else None
            by_node = sched.preemption_victims(body.get("Pod") or {}, candidates)
            meta = {}
            for node, keys in by_node.items():
                pods = []
                for key in keys:
                    ns, name = key.split("/", 1)
                    try:
                        uid = sched.api.get_pod(ns, name).get("metadata", {}).get("uid", key)
                    except Exception:  # noqa: BLE001
                        uid = key
                    pods.append({"UID": uid})
                meta[node] = {"Pods": pods, "NumPDBViolations": 0}
            return {"NodeNameToMetaVictims": meta}

    return Handler


def _debug_state(sched: Scheduler) -> dict:
    views = sched.cache.views()
    return {
        "nodes": sched.cache.node_names(),
        "slices": {
            sid: {
                "mesh": list(v.mesh_shape),
                "free": sorted(list(c) for c in v.free),
                "used": sorted(list(c) for c in v.used),
                "hosts": v.hosts(),
            }
            for sid, v in views.items()
        },
        # in-flight gang admissions (plans reserve every member up front);
        # locked snapshot accessors — handler threads race the verbs
        "gang_plans": sched.groups.plans_snapshot(),
        "assumed": sched.cache.assumed_keys(),
    }


class _ExtenderHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def handle_error(self, request, client_address):
        # routine connection noise (failed TLS handshakes from probes and
        # scanners, clients dropping mid-request) — log, don't spray
        # tracebacks to stderr
        log.debug("connection error from %s", client_address, exc_info=True)


class ExtenderServer:
    """Owns the HTTP server + node/pod watches + a cache resync loop.

    The node watch is the fast path of failure detection: the advertiser's
    node patch lands as an event and chip-death eviction fires immediately
    instead of waiting for the next resync tick.  The pod watch is the fast
    path of gang lifecycle: a deleted member invalidates its gang plan and
    frees its chips the moment the DELETED event lands, instead of waiting
    out the plan TTL or the next resync LIST (SURVEY.md §3.5 — the
    reference ran BOTH informers).  The periodic resync stays as the
    consistency backstop (watch-stream drops, missed events, the
    orphaned-node sweep)."""

    def __init__(
        self,
        sched: Scheduler,
        listen: Tuple[str, int] = ("127.0.0.1", 12345),
        resync_interval_s: float = 30.0,
        watch: bool = True,
        elector=None,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
        auth_token: Optional[str] = None,
    ) -> None:
        self.sched = sched
        self.elector = elector  # utils.leaderelection.LeaderElector or None
        if elector is not None:
            # fencing re-check before the durable annotation write: a bind
            # that entered the verb gate just before the lease window
            # closed must not commit after a promoted standby may have
            # re-allocated the chips.  (A Lease is not a true fencing
            # token — an already-issued PATCH can still land late; the
            # conflict sweep's durable double-annotation eviction is the
            # final backstop for that residue.)
            sched.serving_gate = self._is_leader
        self.httpd = _ExtenderHTTPServer(
            listen,
            make_handler(
                sched,
                self._is_leader if elector else None,
                auth_token=auth_token,
            ),
        )
        self.tls = bool(tls_cert and tls_key)
        if self.tls:
            # serve the extender verbs over HTTPS (VERDICT r3 missing #2):
            # /bind and /preemption are privileged writes, and the client
            # side (KubeApiServer) already does TLS+bearer — the server
            # side must match.  Plain HTTP stays available for dev
            # (--fake-cluster demos) by simply not passing cert/key.
            #
            # do_handshake_on_connect=False is load-bearing: with the
            # default, the TLS handshake runs inside accept() on the ONE
            # serve_forever thread — a client that connects and never
            # speaks would stall every verb AND the health probes.  The
            # handshake instead runs in the per-connection handler thread
            # (Handler.setup) under a deadline.
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket,
                server_side=True,
                do_handshake_on_connect=False,
            )
        self.resync_interval_s = resync_interval_s
        self.watch = watch
        self._stop = threading.Event()
        self._threads = []

    def _is_leader(self) -> bool:
        return self.elector is None or self.elector.is_leader()

    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    def start(self) -> None:
        self.sched.cache.refresh()
        if self.elector is not None:
            e = threading.Thread(
                target=self.elector.run, args=(self._stop,), daemon=True
            )
            e.start()
            self._threads.append(e)
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        r = threading.Thread(target=self._resync_loop, daemon=True)
        r.start()
        self._threads.append(r)
        if self.watch:
            w = threading.Thread(target=self._watch_loop, daemon=True)
            w.start()
            self._threads.append(w)
            p = threading.Thread(target=self._pod_watch_loop, daemon=True)
            p.start()
            self._threads.append(p)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_interval_s):
            try:
                if self._is_leader():
                    # refresh + dead-chip eviction sweep (failure detector)
                    self.sched.resync()
                else:
                    # warm standby (SURVEY §1: durable state lives in the
                    # API server): keep the cache replaying annotations so
                    # promotion is instant, but sweep/evict NOTHING — only
                    # the leader acts on the cluster
                    self.sched.cache.refresh()
            except Exception:  # noqa: BLE001
                log.exception("cache resync failed; keeping stale cache")

    def _watch_loop(self) -> None:
        def handler(event: str, obj: dict) -> None:
            try:
                if event == "node-updated":
                    if self._is_leader():
                        self.sched.on_node_updated(obj)
                    else:
                        # standby: track topology for cache warmth, never
                        # evict (cache has its own lock; the strike maps
                        # the lifecycle lock guards are leader-only state)
                        self.sched.cache.update_node(obj)
                # node-deleted: left to resync's orphan sweep, which owns
                # the absence-grace bookkeeping (one LIST blip ≠ node loss)
            except Exception:  # noqa: BLE001
                log.exception("node watch handler failed for %s", event)

        try:
            self.sched.api.watch_nodes(handler, self._stop)
        except NotImplementedError:
            log.info("api server has no node watch; relying on periodic resync")
        except Exception:  # noqa: BLE001
            log.exception("node watch died; relying on periodic resync")

    def _pod_watch_loop(self) -> None:
        def handler(event: str, obj: dict) -> None:
            try:
                if event == "pod-deleted" and self._is_leader():
                    # standbys skip: they hold no plans/reservations to
                    # free, and the GET-confirm round-trip is the leader's
                    # to spend; their cache converges via refresh()
                    self.sched.on_pod_deleted(obj)
                # pod-created needs no action here: planning happens in
                # filter, which kube-scheduler re-drives for pending pods —
                # and the deletion path above has already freed whatever a
                # fresh plan needs.  pod-updated is reconciled by resync.
            except Exception:  # noqa: BLE001
                log.exception("pod watch handler failed for %s", event)

        try:
            self.sched.api.watch_pods(handler, self._stop)
        except NotImplementedError:
            log.info("api server has no pod watch; relying on plan TTL + resync")
        except Exception:  # noqa: BLE001
            log.exception("pod watch died; relying on plan TTL + resync")

    def stop(self) -> None:
        self._stop.set()
        if self.elector is not None:
            # release the lease NOW, synchronously — the elector thread is
            # a daemon and may be killed at interpreter exit before its
            # own run()-exit release runs; without this a rolling update
            # leaves the dead pod's holderIdentity on the lease and the
            # standby waits out the whole observation window (a leaderless
            # stretch where every verb 503s)
            self.elector.release()
        close = getattr(self.sched.api, "close_watches", None)
        if close is not None:
            close()  # unblock watch threads from quiet-window socket reads
        self.httpd.shutdown()
        self.httpd.server_close()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

FAKE_PRESETS = {
    # name -> (mesh_shape, host_block)
    "v5e-4": ((2, 2), (2, 2)),
    "v5e-8": ((2, 4), (2, 2)),
    "v5e-16": ((4, 4), (2, 2)),
    "v5e-32": ((4, 8), (2, 2)),
    "v5e-64": ((8, 8), (2, 2)),
    "v5e-256": ((16, 16), (2, 2)),
}


def build_fake_cluster(preset: str) -> ApiServer:
    """Fabricated in-memory cluster; an ``Nx-`` prefix (e.g. ``2x-v5e-16``)
    advertises N DCN-connected slices of the preset, for driving multislice
    scheduling from curl."""
    from kubegpu_tpu.plugins import Advertiser, FakeSlice

    count, base = 1, preset
    if "x-" in preset:
        head, _, rest = preset.partition("x-")
        if head.isdigit() and int(head) > 0:
            count, base = int(head), rest
    if base not in FAKE_PRESETS:
        raise SystemExit(f"unknown preset {preset}; choose from {sorted(FAKE_PRESETS)}")
    mesh, block = FAKE_PRESETS[base]
    api = InMemoryApiServer()
    for i in range(count):
        suffix = f"-{chr(ord('a') + i)}" if count > 1 else ""
        fs = FakeSlice(
            slice_id=f"fake-{base}{suffix}", mesh_shape=mesh, host_block=block
        )
        for host, prov in fs.providers().items():
            Advertiser(prov, api).advertise_once()
    return api


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--listen", default="127.0.0.1:12345")
    ap.add_argument(
        "--fake-cluster",
        metavar="PRESET",
        help="serve a fabricated in-memory cluster (e.g. v5e-16) instead of "
        "connecting to a real API server",
    )
    ap.add_argument("--resync-interval", type=float, default=30.0)
    ap.add_argument(
        "--plugin",
        action="append",
        default=[],
        metavar="MODULE[:FACTORY]",
        help="load an extra DeviceSchedulerPlugin (SURVEY.md §3.5 plugin "
        "loading); FACTORY defaults to create_device_scheduler_plugin",
    )
    ap.add_argument(
        "--tls-cert",
        help="serve the extender over HTTPS with this PEM certificate "
        "(pair with --tls-key; omit both for plain-HTTP dev mode)",
    )
    ap.add_argument("--tls-key", help="PEM private key for --tls-cert")
    ap.add_argument(
        "--auth-token-file",
        help="require 'Authorization: Bearer <token>' (file contents) on "
        "the privileged verbs /bind and /preemption",
    )
    ap.add_argument(
        "--leader-elect",
        action="store_true",
        help="run with coordination.k8s.io Lease leader election: only the "
        "lease holder serves verbs and acts on the cluster; standbys keep "
        "a warm cache and answer 503 so kube-scheduler retries.  Makes "
        "replicas>1 safe (HA)",
    )
    ap.add_argument("--lease-namespace", default="kube-system")
    ap.add_argument("--lease-name", default="kubegpu-tpu-scheduler")
    ap.add_argument(
        "--identity",
        default=None,
        help="lease holder identity (default hostname_pid)",
    )
    ap.add_argument(
        "--preemption-min-runtime",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="anti-starvation shield: a freshly-admitted unit (pod or "
        "whole gang) is non-preemptible for this long after its last "
        "member binds, so alternating higher-priority tenants cannot "
        "starve it forever (0 disables)",
    )
    ap.add_argument(
        "--no-active-preemption",
        action="store_true",
        help="do not evict victims inside filter; only nominate them via "
        "the advisory /preemption verb (kube-scheduler performs the "
        "evictions — the classic extender division of labor)",
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)

    if args.fake_cluster:
        api = build_fake_cluster(args.fake_cluster)
    else:
        from kubegpu_tpu.utils.apiserver import KubeApiServer

        api = KubeApiServer()
    from kubegpu_tpu.scheduler.plugins import default_registry

    registry = default_registry()
    for spec in args.plugin:
        registry.load(spec)
    host, _, port = args.listen.rpartition(":")
    sched = Scheduler(
        api,
        plugins=registry,
        active_preemption=not args.no_active_preemption,
        preemption_min_runtime_s=args.preemption_min_runtime,
    )
    elector = None
    if args.leader_elect:
        import os
        import socket as _socket

        from kubegpu_tpu.utils.leaderelection import LeaderElector

        elector = LeaderElector(
            api,
            identity=args.identity or f"{_socket.gethostname()}_{os.getpid()}",
            namespace=args.lease_namespace,
            name=args.lease_name,
            # a freshly-promoted leader replays annotations before its
            # first verb, so the cache it binds against is current
            on_started_leading=sched.cache.refresh,
        )
    auth_token = None
    if args.auth_token_file:
        with open(args.auth_token_file) as f:
            auth_token = f.read().strip()
    if bool(args.tls_cert) != bool(args.tls_key):
        raise SystemExit("--tls-cert and --tls-key must be given together")
    server = ExtenderServer(
        sched,
        listen=(host or "127.0.0.1", int(port)),
        resync_interval_s=args.resync_interval,
        elector=elector,
        tls_cert=args.tls_cert,
        tls_key=args.tls_key,
        auth_token=auth_token,
    )
    server.start()
    log.info(
        "extender listening on %s://%s:%d",
        "https" if server.tls else "http",
        *server.address,
    )
    # SIGTERM is the deployed shutdown path (kubelet, rolling updates):
    # without a handler Python dies without unwinding, the lease release
    # never runs, and the standby waits out the full lease window
    import signal

    shutdown = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: shutdown.set())
    try:
        shutdown.wait()
    except KeyboardInterrupt:
        pass
    server.stop()


if __name__ == "__main__":
    main()
