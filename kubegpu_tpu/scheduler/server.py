"""Scheduler-extender HTTP service (SURVEY.md §2 #4, §3.1).

Implements the Kubernetes scheduler-extender wire API — POST /filter,
/prioritize, /bind with the upstream JSON shapes — plus /healthz, /metrics
(Prometheus text) and /state (debug dump), mirroring the observability the
rebuild adds over the reference (SURVEY.md §5.1/§5.5).

Run in-cluster against the real API server:
    python -m kubegpu_tpu.scheduler.server --listen 0.0.0.0:12345

or self-hosted on a fabricated cluster for demos/tests (no k8s needed):
    python -m kubegpu_tpu.scheduler.server --fake-cluster v5e-16

Register with kube-scheduler via deploy/extender-policy.json.
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from kubegpu_tpu.scheduler.core import Scheduler
from kubegpu_tpu.utils.apiserver import ApiServer, InMemoryApiServer

log = logging.getLogger(__name__)


def make_handler(sched: Scheduler):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # -- plumbing ----------------------------------------------------
        def _read_json(self) -> Optional[dict]:
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                return json.loads(raw) if raw else {}
            except (ValueError, json.JSONDecodeError):
                return None

        def _send(self, code: int, payload, content_type="application/json") -> None:
            body = (
                json.dumps(payload).encode()
                if content_type == "application/json"
                else payload.encode()
            )
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # route through logging, not stderr
            log.debug("http: " + fmt, *args)

        # -- verbs -------------------------------------------------------
        def do_DELETE(self):
            parts = self.path.strip("/").split("/")
            if (
                len(parts) == 3
                and parts[0] == "pods"
                and isinstance(sched.api, InMemoryApiServer)
            ):
                ns, name = parts[1], parts[2]
                try:
                    obj = sched.api.get_pod(ns, name)
                    sched.api.delete_pod(ns, name)
                    sched.on_pod_deleted(obj)
                    self._send(200, {"deleted": f"{ns}/{name}"})
                except Exception as e:  # noqa: BLE001
                    self._send(404, {"error": str(e)})
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, "ok", content_type="text/plain")
            elif self.path == "/metrics":
                self._send(200, sched.metrics.render(), content_type="text/plain")
            elif self.path == "/state":
                self._send(200, _debug_state(sched))
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            body = self._read_json()
            if body is None:
                self._send(400, {"Error": "malformed JSON body"})
                return
            try:
                if self.path == "/filter":
                    self._send(200, self._filter(body))
                elif self.path == "/prioritize":
                    self._send(200, self._prioritize(body))
                elif self.path == "/bind":
                    self._send(200, self._bind(body))
                elif self.path == "/preemption":
                    self._send(200, self._preemption(body))
                elif self.path == "/pods" and isinstance(sched.api, InMemoryApiServer):
                    # fake-cluster demo mode only: lets curl drive the full
                    # filter→prioritize→bind flow without a real API server
                    self._send(200, sched.api.create_pod(body))
                else:
                    self._send(404, {"error": f"no route {self.path}"})
            except Exception as e:  # noqa: BLE001 - extender must never hang kube-scheduler
                log.exception("handler error on %s", self.path)
                self._send(200, {"Error": f"internal error: {e}"})

        def _candidates(self, body: dict) -> Tuple[list, bool]:
            """Extender args carry either NodeNames (preferred) or full
            Nodes.Items; return (names, used_full_objects)."""
            if body.get("NodeNames"):
                return list(body["NodeNames"]), False
            items = (body.get("Nodes") or {}).get("Items") or []
            return [n.get("metadata", {}).get("name", "") for n in items], True

        def _filter(self, body: dict) -> dict:
            names, full = self._candidates(body)
            result = sched.filter(body.get("Pod") or {}, names)
            out = {
                "NodeNames": result.nodes,
                "FailedNodes": result.failed,
                "Error": result.error,
            }
            if full:
                items = (body.get("Nodes") or {}).get("Items") or []
                keep = set(result.nodes)
                out["Nodes"] = {
                    "Items": [
                        n for n in items if n.get("metadata", {}).get("name") in keep
                    ]
                }
            return out

        def _prioritize(self, body: dict) -> list:
            names, _ = self._candidates(body)
            scores = sched.prioritize(body.get("Pod") or {}, names)
            return [{"Host": h, "Score": s} for h, s in scores]

        def _bind(self, body: dict) -> dict:
            err = sched.bind(
                body.get("PodNamespace", "default"),
                body.get("PodName", ""),
                body.get("Node", ""),
            )
            return {"Error": err or ""}

        def _preemption(self, body: dict) -> dict:
            """ExtenderPreemptionArgs -> NodeNameToMetaVictims (advisory:
            kube-scheduler performs the eviction for this verb).  The
            nominated-node map scopes which slices victims may come from."""
            nominated = (
                body.get("NodeNameToVictims") or body.get("NodeNameToMetaVictims") or {}
            )
            candidates = sorted(nominated) if nominated else None
            by_node = sched.preemption_victims(body.get("Pod") or {}, candidates)
            meta = {}
            for node, keys in by_node.items():
                pods = []
                for key in keys:
                    ns, name = key.split("/", 1)
                    try:
                        uid = sched.api.get_pod(ns, name).get("metadata", {}).get("uid", key)
                    except Exception:  # noqa: BLE001
                        uid = key
                    pods.append({"UID": uid})
                meta[node] = {"Pods": pods, "NumPDBViolations": 0}
            return {"NodeNameToMetaVictims": meta}

    return Handler


def _debug_state(sched: Scheduler) -> dict:
    views = sched.cache.views()
    return {
        "nodes": sched.cache.node_names(),
        "slices": {
            sid: {
                "mesh": list(v.mesh_shape),
                "free": sorted(list(c) for c in v.free),
                "used": sorted(list(c) for c in v.used),
                "hosts": v.hosts(),
            }
            for sid, v in views.items()
        },
        # in-flight gang admissions (plans reserve every member up front);
        # locked snapshot accessors — handler threads race the verbs
        "gang_plans": sched.groups.plans_snapshot(),
        "assumed": sched.cache.assumed_keys(),
    }


class ExtenderServer:
    """Owns the HTTP server + node/pod watches + a cache resync loop.

    The node watch is the fast path of failure detection: the advertiser's
    node patch lands as an event and chip-death eviction fires immediately
    instead of waiting for the next resync tick.  The pod watch is the fast
    path of gang lifecycle: a deleted member invalidates its gang plan and
    frees its chips the moment the DELETED event lands, instead of waiting
    out the plan TTL or the next resync LIST (SURVEY.md §3.5 — the
    reference ran BOTH informers).  The periodic resync stays as the
    consistency backstop (watch-stream drops, missed events, the
    orphaned-node sweep)."""

    def __init__(
        self,
        sched: Scheduler,
        listen: Tuple[str, int] = ("127.0.0.1", 12345),
        resync_interval_s: float = 30.0,
        watch: bool = True,
    ) -> None:
        self.sched = sched
        self.httpd = ThreadingHTTPServer(listen, make_handler(sched))
        self.resync_interval_s = resync_interval_s
        self.watch = watch
        self._stop = threading.Event()
        self._threads = []

    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    def start(self) -> None:
        self.sched.cache.refresh()
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        r = threading.Thread(target=self._resync_loop, daemon=True)
        r.start()
        self._threads.append(r)
        if self.watch:
            w = threading.Thread(target=self._watch_loop, daemon=True)
            w.start()
            self._threads.append(w)
            p = threading.Thread(target=self._pod_watch_loop, daemon=True)
            p.start()
            self._threads.append(p)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_interval_s):
            try:
                # refresh + dead-chip eviction sweep (the failure detector)
                self.sched.resync()
            except Exception:  # noqa: BLE001
                log.exception("cache resync failed; keeping stale cache")

    def _watch_loop(self) -> None:
        def handler(event: str, obj: dict) -> None:
            try:
                if event == "node-updated":
                    self.sched.on_node_updated(obj)
                # node-deleted: left to resync's orphan sweep, which owns
                # the absence-grace bookkeeping (one LIST blip ≠ node loss)
            except Exception:  # noqa: BLE001
                log.exception("node watch handler failed for %s", event)

        try:
            self.sched.api.watch_nodes(handler, self._stop)
        except NotImplementedError:
            log.info("api server has no node watch; relying on periodic resync")
        except Exception:  # noqa: BLE001
            log.exception("node watch died; relying on periodic resync")

    def _pod_watch_loop(self) -> None:
        def handler(event: str, obj: dict) -> None:
            try:
                if event == "pod-deleted":
                    self.sched.on_pod_deleted(obj)
                # pod-created needs no action here: planning happens in
                # filter, which kube-scheduler re-drives for pending pods —
                # and the deletion path above has already freed whatever a
                # fresh plan needs.  pod-updated is reconciled by resync.
            except Exception:  # noqa: BLE001
                log.exception("pod watch handler failed for %s", event)

        try:
            self.sched.api.watch_pods(handler, self._stop)
        except NotImplementedError:
            log.info("api server has no pod watch; relying on plan TTL + resync")
        except Exception:  # noqa: BLE001
            log.exception("pod watch died; relying on plan TTL + resync")

    def stop(self) -> None:
        self._stop.set()
        close = getattr(self.sched.api, "close_watches", None)
        if close is not None:
            close()  # unblock watch threads from quiet-window socket reads
        self.httpd.shutdown()
        self.httpd.server_close()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

FAKE_PRESETS = {
    # name -> (mesh_shape, host_block)
    "v5e-4": ((2, 2), (2, 2)),
    "v5e-8": ((2, 4), (2, 2)),
    "v5e-16": ((4, 4), (2, 2)),
    "v5e-32": ((4, 8), (2, 2)),
    "v5e-64": ((8, 8), (2, 2)),
    "v5e-256": ((16, 16), (2, 2)),
}


def build_fake_cluster(preset: str) -> ApiServer:
    """Fabricated in-memory cluster; an ``Nx-`` prefix (e.g. ``2x-v5e-16``)
    advertises N DCN-connected slices of the preset, for driving multislice
    scheduling from curl."""
    from kubegpu_tpu.plugins import Advertiser, FakeSlice

    count, base = 1, preset
    if "x-" in preset:
        head, _, rest = preset.partition("x-")
        if head.isdigit() and int(head) > 0:
            count, base = int(head), rest
    if base not in FAKE_PRESETS:
        raise SystemExit(f"unknown preset {preset}; choose from {sorted(FAKE_PRESETS)}")
    mesh, block = FAKE_PRESETS[base]
    api = InMemoryApiServer()
    for i in range(count):
        suffix = f"-{chr(ord('a') + i)}" if count > 1 else ""
        fs = FakeSlice(
            slice_id=f"fake-{base}{suffix}", mesh_shape=mesh, host_block=block
        )
        for host, prov in fs.providers().items():
            Advertiser(prov, api).advertise_once()
    return api


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--listen", default="127.0.0.1:12345")
    ap.add_argument(
        "--fake-cluster",
        metavar="PRESET",
        help="serve a fabricated in-memory cluster (e.g. v5e-16) instead of "
        "connecting to a real API server",
    )
    ap.add_argument("--resync-interval", type=float, default=30.0)
    ap.add_argument(
        "--plugin",
        action="append",
        default=[],
        metavar="MODULE[:FACTORY]",
        help="load an extra DeviceSchedulerPlugin (SURVEY.md §3.5 plugin "
        "loading); FACTORY defaults to create_device_scheduler_plugin",
    )
    ap.add_argument(
        "--no-active-preemption",
        action="store_true",
        help="do not evict victims inside filter; only nominate them via "
        "the advisory /preemption verb (kube-scheduler performs the "
        "evictions — the classic extender division of labor)",
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)

    if args.fake_cluster:
        api = build_fake_cluster(args.fake_cluster)
    else:
        from kubegpu_tpu.utils.apiserver import KubeApiServer

        api = KubeApiServer()
    from kubegpu_tpu.scheduler.plugins import default_registry

    registry = default_registry()
    for spec in args.plugin:
        registry.load(spec)
    host, _, port = args.listen.rpartition(":")
    server = ExtenderServer(
        Scheduler(
            api,
            plugins=registry,
            active_preemption=not args.no_active_preemption,
        ),
        listen=(host or "127.0.0.1", int(port)),
        resync_interval_s=args.resync_interval,
    )
    server.start()
    log.info("extender listening on %s:%d", *server.address)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
