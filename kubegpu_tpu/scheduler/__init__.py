"""L4 scheduling service (SURVEY.md §2 #4): cache, core verbs, gang
registry, HTTP extender server."""

from kubegpu_tpu.scheduler.cache import ClusterCache
from kubegpu_tpu.scheduler.core import FilterResult, Scheduler
from kubegpu_tpu.scheduler.plugins import (
    DeviceSchedulerPlugin,
    GroupedResourceScheduler,
    PluginRegistry,
    TpuDeviceScheduler,
    default_registry,
)
from kubegpu_tpu.scheduler.podgroup import GangPlan, PodGroupRegistry
from kubegpu_tpu.scheduler.server import ExtenderServer, build_fake_cluster

__all__ = [
    "ClusterCache",
    "FilterResult",
    "Scheduler",
    "GangPlan",
    "PodGroupRegistry",
    "ExtenderServer",
    "build_fake_cluster",
    "DeviceSchedulerPlugin",
    "GroupedResourceScheduler",
    "PluginRegistry",
    "TpuDeviceScheduler",
    "default_registry",
]
