"""DeviceScheduler plugins: pluggable device types for the extender.

Capability parity with the reference's scheduler-side plugin architecture
(SURVEY.md §2 #5, §3.5): the extender loads DeviceScheduler plugins, each of
which translates a pod's container requests for ONE device type into an
allocator query and delegates fit/score to the allocation core.  The
reference loaded Go ``plugin`` .so files resolved through a
``CreateDeviceSchedulerPlugin`` entry symbol; the Python-native analog is
:func:`PluginRegistry.load` — an importlib module path resolved through a
``create_device_scheduler_plugin()`` factory.

Two built-ins:

- :class:`TpuDeviceScheduler` — the TPU path (the analog of the reference's
  GPU scheduler plugin): scalar ``google.com/tpu`` requests, ICI-mesh
  fit/score via ``grpalloc.pod_fits_group_constraints``.
- :class:`GroupedResourceScheduler` — arbitrary extended resources (e.g. a
  vendor accelerator advertised as a grouped tree): scalar requests expand
  to wildcard tree requests (``grpalloc.treefit``, SURVEY.md §2 #3) and fit
  against the node's allocatable tree; bindings ride the same Assignment
  annotation / cache bookkeeping as chips.

A pod may request ONE device type: the scheduler rejects pods whose
containers mix device types (``Scheduler._owning_plugin``), because a single
Assignment annotation can only commit one plugin's allocation atomically.
``PluginRegistry.plugin_for`` resolves a single-type pod to its owning
plugin by registration order (TPU first).
"""

from __future__ import annotations

import importlib
import logging
from abc import ABC, abstractmethod
from typing import Dict, List, Optional

from kubegpu_tpu.grpalloc import pod_fits_group_constraints
from kubegpu_tpu.grpalloc.allocator import FitResult
from kubegpu_tpu.grpalloc.treefit import expand_scalar_request, fit_request_tree
from kubegpu_tpu.grpalloc.view import SliceView
from kubegpu_tpu.types.info import Assignment, NodeInfo, PodInfo, TpuRequest
from kubegpu_tpu.types.resource import ResourcePath, ResourceTree

log = logging.getLogger(__name__)

ENTRY_SYMBOL = "create_device_scheduler_plugin"


class DeviceSchedulerPlugin(ABC):
    """One device type's scheduling logic (SURVEY.md §2 #5)."""

    name: str = "device"

    @abstractmethod
    def owns(self, pod: PodInfo) -> bool:
        """Does this pod request this plugin's device type?"""

    @abstractmethod
    def fit(
        self, node: NodeInfo, pod: PodInfo, view: Optional[SliceView]
    ) -> FitResult:
        """Feasibility + concrete assignment + score on one node."""


class TpuDeviceScheduler(DeviceSchedulerPlugin):
    """The built-in TPU plugin: delegates to the ICI-mesh allocation core."""

    name = "tpu"

    def __init__(self) -> None:
        # one-slot request memo: a filter/prioritize sweep calls fit() once
        # per candidate node for the SAME pod object; don't rebuild the
        # request N times (identity check, strong ref — no aliasing risk)
        self._last: Optional[tuple] = None

    def owns(self, pod: PodInfo) -> bool:
        return pod.total_tpu_chips() > 0

    def _request(self, pod: PodInfo) -> TpuRequest:
        if self._last is not None and self._last[0] is pod:
            return self._last[1]
        req = TpuRequest.from_pod(pod)
        self._last = (pod, req)
        return req

    def fit(
        self, node: NodeInfo, pod: PodInfo, view: Optional[SliceView]
    ) -> FitResult:
        return pod_fits_group_constraints(node, self._request(pod), view)


class GroupedResourceScheduler(DeviceSchedulerPlugin):
    """Generic grouped-resource device type.

    ``resource_name`` is the extended-resource key in container limits
    (e.g. ``example.com/npu``); ``template`` is the wildcard path a scalar
    request expands into (e.g. ``npugrp/*/npu/*/dev`` — SURVEY.md §2 #3).
    Capacity comes from the node's grouped-capacity annotation
    (``annotations.NODE_GROUPED_CAPACITY``), written by the device's own
    advertiser daemon.
    """

    def __init__(self, name: str, resource_name: str, template: str) -> None:
        self.name = name
        self.resource_name = resource_name
        self.template = template

    def owns(self, pod: PodInfo) -> bool:
        return any(c.extended.get(self.resource_name, 0) > 0 for c in pod.containers)

    def fit(
        self, node: NodeInfo, pod: PodInfo, view: Optional[SliceView]
    ) -> FitResult:
        allocatable = node.allocatable()
        leaf = self.template.rsplit("/", 1)[-1]
        free_before = allocatable.total(leaf)
        grouped: Dict[str, List] = {}
        want_total = 0
        for c in pod.containers:
            want = c.extended.get(self.resource_name, 0)
            if want <= 0:
                continue
            want_total += want
            request = expand_scalar_request(self.resource_name, want, self.template)
            r = fit_request_tree(request, allocatable)
            if not r.fits:
                return FitResult(
                    fits=False,
                    reason=f"{self.resource_name} on {node.name}: {r.reason}",
                    capacity_failure=True,
                )
            bindings: List = []
            for pairs in r.bindings.values():
                bindings.extend((path, qty) for path, qty in pairs)
            grouped[c.name] = bindings
            # later containers must not re-bind the same units
            for path_s, qty in bindings:
                single = ResourceTree()
                single.add(ResourcePath.parse(path_s), qty)
                allocatable.add_tree(single, sign=-1)
        if want_total == 0:
            return FitResult(fits=True, reason="no device request", score=0.0)
        # bin-packing score: prefer the node whose matching capacity is
        # tightest after placement (the multi-tenant packing stance the
        # TPU scorer takes, applied to flat quantities)
        free_after = free_before - want_total
        score = 100.0 / (1.0 + max(0, free_after))
        return FitResult(
            fits=True,
            score=score,
            assignment=Assignment(
                node=node.name, slice_id=None, grouped=grouped, score=score
            ),
        )


class PluginRegistry:
    """Ordered plugin set; first ``owns()`` match wins (§3.5 plugin load)."""

    def __init__(self) -> None:
        self._plugins: List[DeviceSchedulerPlugin] = []

    def register(self, plugin: DeviceSchedulerPlugin) -> None:
        if any(p.name == plugin.name for p in self._plugins):
            raise ValueError(f"plugin {plugin.name!r} already registered")
        self._plugins.append(plugin)
        log.info("registered device-scheduler plugin %s", plugin.name)

    def plugin_for(self, pod: PodInfo) -> Optional[DeviceSchedulerPlugin]:
        for p in self._plugins:
            if p.owns(pod):
                return p
        return None

    def plugins_for(self, pod: PodInfo) -> List[DeviceSchedulerPlugin]:
        """Every plugin claiming this pod.  More than one means the pod mixes
        device types — the scheduler must REJECT it rather than silently fit
        only the first type (which would over-commit the others)."""
        return [p for p in self._plugins if p.owns(pod)]

    def names(self) -> List[str]:
        return [p.name for p in self._plugins]

    def load(self, spec: str) -> DeviceSchedulerPlugin:
        """Dynamic loading, the Go-plugin .so analog (SURVEY.md §2 #5):
        ``spec`` is ``module`` or ``module:factory``; the factory (default
        ``create_device_scheduler_plugin``) returns the plugin instance."""
        module_name, _, symbol = spec.partition(":")
        mod = importlib.import_module(module_name)
        factory = getattr(mod, symbol or ENTRY_SYMBOL)
        plugin = factory()
        if not isinstance(plugin, DeviceSchedulerPlugin):
            raise TypeError(
                f"{spec}: factory returned {type(plugin).__name__}, "
                "not a DeviceSchedulerPlugin"
            )
        self.register(plugin)
        return plugin


def default_registry() -> PluginRegistry:
    reg = PluginRegistry()
    reg.register(TpuDeviceScheduler())
    return reg
