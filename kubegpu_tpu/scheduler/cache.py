"""ClusterCache: the extender's only mutable state — rebuilt from the API
server at any time.

Parity with the reference's in-memory cluster cache (SURVEY.md §2 #4, §3.5):
nodes' device trees decoded from annotations; in-flight + bound allocations
replayed into per-node used-trees.  Because every allocation is durably
recorded in pod annotations at bind time, a restarted scheduler calls
``refresh()`` and is exactly where it left off — no database (§1 data-flow
contract).  Thread-safe: filter/prioritize run concurrently; mutations are
serialized (SURVEY.md §7 hard part (c))."""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from kubegpu_tpu.grpalloc import (
    SliceView,
    build_slice_views,
    return_pod_resources,
    take_pod_resources,
)
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.info import Assignment, NodeInfo
from kubegpu_tpu.utils.apiserver import ApiServer, NotFound

log = logging.getLogger(__name__)


def _live_assignment(obj: dict) -> Optional[Assignment]:
    """A pod's assignment FOR CHARGING purposes: terminal-phase pods
    (Succeeded/Failed) hold nothing — their containers are done and will
    never run again, so their chips are free the moment the phase lands,
    annotation lingering or not (standard kube-scheduler accounting).
    The annotation itself is left in place: it is history, not a claim."""
    phase = ((obj.get("status") or {}).get("phase") or "")
    if phase in ("Succeeded", "Failed"):
        return None
    return annotations.assignment_from_pod(obj)


class ClusterCache:
    def __init__(self, api: ApiServer) -> None:
        self.api = api
        self._lock = threading.RLock()
        self._nodes: Dict[str, NodeInfo] = {}
        # pod key -> Assignment, both bound (from annotations) and assumed
        # (bind in flight); the used-trees of _nodes are derived from this
        self._assignments: Dict[str, Assignment] = {}
        # keys reserved in memory but not yet durably annotated (gang plans,
        # binds in flight).  refresh() must carry these over — wiping them
        # would let the resync loop double-allocate chips under a live plan.
        self._assumed: set = set()
        # pod key -> node name, for annotated pods whose node was NOT in the
        # last refresh's LIST.  These never enter _assignments (no tree to
        # charge), but the failure detector must still see them: a vanished
        # node (dead advertiser, deregistered VM) is precisely the case
        # where no future advertisement will ever evict the pod.
        self._orphaned: Dict[str, str] = {}
        # pod key -> node name, for records whose chips CONFLICTED at the
        # last refresh (another record holds the charge).  Usually a
        # transient race that the next refresh clears; if it persists, two
        # live annotations claim one chip — a pathological durable state
        # the scheduler's conflict sweep resolves by evicting the uncharged
        # claimant after a grace window.  Tracked so the pod is never
        # invisible to every detector while bound+annotated.
        self._conflicted: Dict[str, str] = {}

    # -- building ---------------------------------------------------------
    def refresh(self) -> None:
        """Reconcile with API-server state (startup + resync): rebuild the
        NODE views from fresh advertisements, keep the LIVE in-memory
        assignments (re-charged onto the fresh nodes), and use the pod LIST
        only to NOMINATE divergence candidates — each confirmed with a
        fresh per-pod GET before anything is adopted or removed.

        Why not rebuild assignments from the LIST (the obvious design, and
        round 1's): the LISTs happen BEFORE the lock (a slow API server
        must not stall every verb), so the snapshot is stale against
        concurrent binds/deletes, and every rebuild-from-snapshot variant
        the threaded soak was pointed at leaked a double-allocation — a
        committed bind wiped because its annotation post-dated the LIST, a
        deleted pod's ghost replayed over the new owner's chips, a ghost
        eviction returning chips the real owner charged.  Reconciliation
        kills the class: stale data can never displace live memory, and
        nothing enters or leaves the assignment map without a
        fresh-as-of-now GET agreeing.  Races that remain only make chips
        look USED one cycle too long (the safe direction) — the next
        refresh converges.

        On a cold start the memory is empty, every annotated pod is a
        nominee, and the GET-confirmed adoptions ARE the restart replay
        (SURVEY.md §3.5 — what makes restarts safe with no database)."""
        from kubegpu_tpu.grpalloc.allocator import clear_fit_caches

        clear_fit_caches()  # bound memo retention to one resync period
        nodes_raw = self.api.list_nodes()
        pods_raw = self.api.list_pods()
        with self._lock:
            self._nodes = {}
            for obj in nodes_raw:
                try:
                    node = annotations.node_from_k8s(obj)
                except Exception:  # noqa: BLE001 - one bad annotation must not
                    log.exception("ignoring undecodable node annotation")
                    continue
                self._nodes[node.name] = node
            # re-charge LIVE memory onto the fresh nodes (never wiped by
            # stale snapshot data); _carry routes vanished-node records to
            # _orphaned and conflicting records to _conflicted
            prev = self._assignments
            prev_assumed = set(self._assumed)
            self._assignments = {}
            self._assumed = set()
            self._orphaned = {}
            self._conflicted = {}
            for key, a in prev.items():
                self._carry(key, a, key in prev_assumed)
            # nominate divergences vs the (stale) snapshot
            listed: Dict[str, Optional[Assignment]] = {}
            for obj in pods_raw:
                meta = obj.get("metadata", {})
                key = f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
                try:
                    listed[key] = _live_assignment(obj)
                except Exception:  # noqa: BLE001
                    log.exception("ignoring undecodable pod assignment")
                    listed[key] = None
            adopt = sorted(
                key
                for key, a in listed.items()
                if a is not None
                and (a.all_chips() or a.grouped)
                and key not in self._assignments
                and key not in self._orphaned
            )
            # memory entries the snapshot does not back: confirmed ones
            # whose annotation is unlisted/cleared (deletion or eviction
            # may have won a race), assumed ones whose pod is unlisted
            # (may simply post-date the LIST — existence decides)
            drop_check = {
                key: (key in self._assumed)
                for key in sorted(self._assignments)
                if (key in self._assumed and key not in listed)
                or (key not in self._assumed and listed.get(key) is None)
            }
            prev_ids = {k: self._assignments[k] for k in drop_check}
        if not adopt and not drop_check:
            return
        # fresh-as-of-now confirmation, UNLOCKED (network; see docstring)
        adopt_now: Dict[str, Assignment] = {}
        for key in adopt:
            cur = self._get_current_assignment(key)
            if isinstance(cur, Assignment):
                adopt_now[key] = cur
        remove_now: Dict[str, Optional[Assignment]] = {}
        for key, assumed in drop_check.items():
            cur = self._get_current_assignment(key)
            if cur == "gone":
                remove_now[key] = None  # pod positively deleted
            elif assumed or cur == "unknown":
                continue  # in-flight pod exists / transient error: keep
            elif not isinstance(cur, Assignment):
                remove_now[key] = None  # annotation cleared: eviction won
            elif (
                annotations.encode_assignment(cur)
                != annotations.encode_assignment(prev_ids[key])
            ):
                remove_now[key] = cur  # re-planned meanwhile: adopt truth
        if not adopt_now and not remove_now:
            return
        with self._lock:
            for key, cur in remove_now.items():
                if self._assignments.get(key) is not prev_ids.get(key):
                    continue  # replaced meanwhile (new bind/plan): theirs wins
                self.remove_pod(key)
                if cur is not None:
                    self._carry(key, cur, False)
            for key, cur in adopt_now.items():
                if key in self._assignments or key in self._orphaned:
                    continue  # bound/planned meanwhile: memory is fresher
                self._carry(key, cur, False)

    def _get_current_assignment(self, key: str):
        """Fresh GET verdict for one pod: an Assignment (live annotation),
        None (exists, no device annotation), "gone" (NotFound), or
        "unknown" (transient error / undecodable — treat conservatively)."""
        ns, name = key.split("/", 1)
        try:
            obj = self.api.get_pod(ns, name)
        except NotFound:
            return "gone"
        except Exception:  # noqa: BLE001
            return "unknown"
        try:
            cur = _live_assignment(obj)
        except Exception:  # noqa: BLE001
            return "unknown"
        if cur is not None and (cur.all_chips() or cur.grouped):
            return cur
        return None

    def _carry(self, key: str, a: Assignment, assumed: bool) -> bool:
        """Charge one adopted/kept assignment (lock held)."""
        node = self._nodes.get(a.node)
        if node is None:
            # node vanished from the LIST: hand the record to the orphan
            # sweep (grace-window eviction owns this case), nothing to charge
            self._orphaned.setdefault(key, a.node)
            return False
        try:
            take_pod_resources(node, a, skip_missing=True)
            self._assignments[key] = a
            if assumed:
                self._assumed.add(key)
            return True
        except ValueError as e:
            # another record holds the charge: track for the conflict
            # sweep instead of letting the pod vanish from every detector
            log.warning("uncharged conflicting record for %s: %s", key, e)
            self._conflicted.setdefault(key, a.node)
            return False

    def update_node(self, obj: dict) -> None:
        """Apply a node watch event: re-decode and re-apply the assignments
        that live on it (a died chip falls out of capacity here)."""
        try:
            node = annotations.node_from_k8s(obj)
        except Exception:  # noqa: BLE001
            log.exception("ignoring undecodable node annotation")
            return
        with self._lock:
            self._nodes[node.name] = node
            for key, a in self._assignments.items():
                if a.node == node.name:
                    try:
                        take_pod_resources(node, a, skip_missing=True)
                    except ValueError as e:
                        log.warning("re-apply of %s on %s: %s", key, node.name, e)

    def remove_pod(self, key: str) -> None:
        """Pod deleted/finished: return its chips — and drop it from EVERY
        detector (a pod deleted while orphan- or conflict-tracked must not
        keep accruing strikes toward evicting an already-deleted pod)."""
        with self._lock:
            self._assumed.discard(key)
            self._orphaned.pop(key, None)
            self._conflicted.pop(key, None)
            a = self._assignments.pop(key, None)
            if a is None:
                return
            node = self._nodes.get(a.node)
            if node is not None:
                return_pod_resources(node, a)

    # -- allocation bookkeeping -------------------------------------------
    def assume(self, key: str, assignment: Assignment) -> None:
        """Reserve an in-flight allocation.  Raises ValueError on any chip
        already taken (bind race) with no state change."""
        with self._lock:
            if key in self._assignments:
                raise ValueError(f"pod {key} already has an assignment")
            node = self._nodes.get(assignment.node)
            if node is None:
                raise KeyError(f"unknown node {assignment.node}")
            take_pod_resources(node, assignment)
            self._assignments[key] = assignment
            self._assumed.add(key)

    def confirm(self, key: str) -> None:
        """Mark a reservation durable (its assignment annotation is written):
        refresh() will from now on rebuild it from the API server."""
        with self._lock:
            self._assumed.discard(key)

    def forget(self, key: str) -> None:
        """Undo a failed bind (SURVEY.md §3.1 failure containment)."""
        self.remove_pod(key)

    # -- queries ----------------------------------------------------------
    def node(self, name: str) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(name)

    def node_names(self) -> List[str]:
        with self._lock:
            return sorted(self._nodes)

    def views(self) -> Dict[str, SliceView]:
        with self._lock:
            return build_slice_views(self._nodes.values())

    def assignment_of(self, key: str) -> Optional[Assignment]:
        with self._lock:
            return self._assignments.get(key)

    def assignments_snapshot(self) -> Dict[str, Assignment]:
        with self._lock:
            return dict(self._assignments)

    def assumed_keys(self) -> List[str]:
        """Locked snapshot of in-flight (reserved, unconfirmed) pod keys."""
        with self._lock:
            return sorted(self._assumed)

    def orphaned_assignments(self) -> Dict[str, str]:
        """pod key -> vanished node name, as of the last refresh()."""
        with self._lock:
            return dict(self._orphaned)

    def conflicted_assignments(self) -> Dict[str, str]:
        """pod key -> node name of records whose chips another record holds
        (uncharged, tracked for the scheduler's conflict sweep)."""
        with self._lock:
            return dict(self._conflicted)

    @property
    def lock(self) -> threading.RLock:
        """Callers that must fit+assume atomically (bind) hold this."""
        return self._lock
