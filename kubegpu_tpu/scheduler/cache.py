"""ClusterCache: the extender's only mutable state — rebuilt from the API
server at any time.

Parity with the reference's in-memory cluster cache (SURVEY.md §2 #4, §3.5):
nodes' device trees decoded from annotations; in-flight + bound allocations
replayed into per-node used-trees.  Because every allocation is durably
recorded in pod annotations at bind time, a restarted scheduler calls
``refresh()`` and is exactly where it left off — no database (§1 data-flow
contract).  Thread-safe: filter/prioritize run concurrently; mutations are
serialized (SURVEY.md §7 hard part (c))."""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from kubegpu_tpu.grpalloc import (
    SliceView,
    build_slice_views,
    return_pod_resources,
    take_pod_resources,
)
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.info import Assignment, NodeInfo
from kubegpu_tpu.utils.apiserver import ApiServer

log = logging.getLogger(__name__)


class ClusterCache:
    def __init__(self, api: ApiServer) -> None:
        self.api = api
        self._lock = threading.RLock()
        self._nodes: Dict[str, NodeInfo] = {}
        # pod key -> Assignment, both bound (from annotations) and assumed
        # (bind in flight); the used-trees of _nodes are derived from this
        self._assignments: Dict[str, Assignment] = {}
        # keys reserved in memory but not yet durably annotated (gang plans,
        # binds in flight).  refresh() must carry these over — wiping them
        # would let the resync loop double-allocate chips under a live plan.
        self._assumed: set = set()
        # pod key -> node name, for annotated pods whose node was NOT in the
        # last refresh's LIST.  These never enter _assignments (no tree to
        # charge), but the failure detector must still see them: a vanished
        # node (dead advertiser, deregistered VM) is precisely the case
        # where no future advertisement will ever evict the pod.
        self._orphaned: Dict[str, str] = {}

    # -- building ---------------------------------------------------------
    def refresh(self) -> None:
        """Full rebuild from API-server state (startup + resync): decode
        node annotations, then replay every scheduled pod's assignment
        (SURVEY.md §3.5 — what makes restarts safe with no database)."""
        nodes_raw = self.api.list_nodes()
        pods_raw = self.api.list_pods()
        with self._lock:
            prev_assumed = {
                k: self._assignments[k]
                for k in self._assumed
                if k in self._assignments
            }
            self._nodes = {}
            self._assignments = {}
            self._assumed = set()
            self._orphaned = {}
            for obj in nodes_raw:
                try:
                    node = annotations.node_from_k8s(obj)
                except Exception:  # noqa: BLE001 - one bad annotation must not
                    log.exception("ignoring undecodable node annotation")
                    continue
                self._nodes[node.name] = node
            live_keys = set()
            for obj in pods_raw:
                meta = obj.get("metadata", {})
                key = f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
                live_keys.add(key)
                try:
                    a = annotations.assignment_from_pod(obj)
                except Exception:  # noqa: BLE001
                    log.exception("ignoring undecodable pod assignment")
                    continue
                if a is None:
                    continue
                self._replay(key, a)
            # carry over in-flight reservations whose pods still exist and
            # have not become durable yet
            for key, a in prev_assumed.items():
                if key in self._assignments or key not in live_keys:
                    continue
                try:
                    node = self._nodes.get(a.node)
                    if node is None:
                        raise KeyError(f"unknown node {a.node}")
                    take_pod_resources(node, a)
                    self._assignments[key] = a
                    self._assumed.add(key)
                except (ValueError, KeyError) as e:
                    log.warning("dropping stale reservation for %s: %s", key, e)

    def _replay(self, key: str, a: Assignment) -> None:
        node = self._nodes.get(a.node)
        if node is None:
            log.warning("assignment for %s names unknown node %s", key, a.node)
            self._orphaned[key] = a.node
            return
        try:
            take_pod_resources(node, a)
        except (ValueError, KeyError) as e:
            # chips vanished or double-booked while we were away; keep the
            # pod's record but do not corrupt the tree
            log.warning("replay of %s partially failed: %s", key, e)
        self._assignments[key] = a

    def update_node(self, obj: dict) -> None:
        """Apply a node watch event: re-decode and re-apply the assignments
        that live on it (a died chip falls out of capacity here)."""
        try:
            node = annotations.node_from_k8s(obj)
        except Exception:  # noqa: BLE001
            log.exception("ignoring undecodable node annotation")
            return
        with self._lock:
            self._nodes[node.name] = node
            for key, a in self._assignments.items():
                if a.node == node.name:
                    try:
                        take_pod_resources(node, a)
                    except (ValueError, KeyError) as e:
                        log.warning("re-apply of %s on %s: %s", key, node.name, e)

    def remove_pod(self, key: str) -> None:
        """Pod deleted/finished: return its chips."""
        with self._lock:
            self._assumed.discard(key)
            a = self._assignments.pop(key, None)
            if a is None:
                return
            node = self._nodes.get(a.node)
            if node is not None:
                return_pod_resources(node, a)

    # -- allocation bookkeeping -------------------------------------------
    def assume(self, key: str, assignment: Assignment) -> None:
        """Reserve an in-flight allocation.  Raises ValueError on any chip
        already taken (bind race) with no state change."""
        with self._lock:
            if key in self._assignments:
                raise ValueError(f"pod {key} already has an assignment")
            node = self._nodes.get(assignment.node)
            if node is None:
                raise KeyError(f"unknown node {assignment.node}")
            take_pod_resources(node, assignment)
            self._assignments[key] = assignment
            self._assumed.add(key)

    def confirm(self, key: str) -> None:
        """Mark a reservation durable (its assignment annotation is written):
        refresh() will from now on rebuild it from the API server."""
        with self._lock:
            self._assumed.discard(key)

    def forget(self, key: str) -> None:
        """Undo a failed bind (SURVEY.md §3.1 failure containment)."""
        self.remove_pod(key)

    # -- queries ----------------------------------------------------------
    def node(self, name: str) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(name)

    def node_names(self) -> List[str]:
        with self._lock:
            return sorted(self._nodes)

    def views(self) -> Dict[str, SliceView]:
        with self._lock:
            return build_slice_views(self._nodes.values())

    def assignment_of(self, key: str) -> Optional[Assignment]:
        with self._lock:
            return self._assignments.get(key)

    def assignments_snapshot(self) -> Dict[str, Assignment]:
        with self._lock:
            return dict(self._assignments)

    def orphaned_assignments(self) -> Dict[str, str]:
        """pod key -> vanished node name, as of the last refresh()."""
        with self._lock:
            return dict(self._orphaned)

    @property
    def lock(self) -> threading.RLock:
        """Callers that must fit+assume atomically (bind) hold this."""
        return self._lock
