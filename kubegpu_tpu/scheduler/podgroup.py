"""Gang scheduling: pod-group plans with all-or-nothing reservation.

The scheduler-extender API is one-pod-at-a-time, which deadlocks naive gang
placement (SURVEY.md §7 hard part (b): partial placements strand chips).
Solution: when the FIRST member of a group reaches filter, gather the whole
group from the API server, run ``grpalloc.fit_gang`` over a slice, and if it
fits **reserve every member's chips in the cache immediately** (assume).
Later members hit the existing plan; bind just confirms.  If the group never
fully arrives or binds stall, the plan expires and its uncommitted
reservations are returned — no leaked chips.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from kubegpu_tpu.grpalloc import fit_gang_multislice
from kubegpu_tpu.grpalloc.multislice import fit_gang_into_layout
from kubegpu_tpu.scheduler.cache import ClusterCache
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.info import Assignment, PodInfo, TpuRequest
from kubegpu_tpu.utils.apiserver import NotFound

log = logging.getLogger(__name__)


def consensus_str(values: List[str]) -> str:
    """Most-common string, ties toward the lexicographically smaller —
    one member carrying a stale pod-group-uid must not move which
    incarnation the gang is judged as.  Lives here (not core) because
    gang_arithmetic applies it for BOTH the planner and the stranded
    sweep — the incarnation is derived identically at every call site."""
    if not values:
        return ""
    counts: Dict[str, int] = {}
    for v in values:
        counts[v] = counts.get(v, 0) + 1
    top = max(counts.values())
    return min(v for v, c in counts.items() if c == top)


def _incarnations_of(pod: "PodInfo", pending, scheduled) -> List[str]:
    """The uid list the planner hands gang_arithmetic — NON-terminating
    members only, mirroring the stranded sweep's ``g["incarnations"]``
    (which skips terminal AND terminating pods before recording a uid).
    During a name-reuse transition the old run's members are exactly the
    terminating ones; including them could flip the consensus to the old
    incarnation and shrink the new run's denominator by the old run's
    completions.  Falls back to the triggering pod's own uid when every
    gathered member is terminating (the sweep would not judge such a gang
    at all, so no divergence is possible there)."""
    uids = [
        p.pod_group_uid for p in pending + scheduled if not p.terminating
    ]
    return uids or [pod.pod_group_uid]


def fold_layout(sched_slices, sched_coords):
    """(layout counts, occupied coords per slice) from a gang's scheduled
    members — the ONE aggregation both planning (try_plan) and preemption
    simulation (layout_and_occupancy_of) consume, so they can never
    drift."""
    layout: Dict[str, int] = {}
    occupied: Dict[str, frozenset] = {}
    for key, sid in sched_slices.items():
        if not sid:
            continue
        layout[sid] = layout.get(sid, 0) + 1
        if key in sched_coords:
            occupied[sid] = occupied.get(sid, frozenset()) | sched_coords[key]
    return layout, occupied


@dataclass
class GangPlan:
    group: str                       # namespace/groupname
    created: float
    per_pod: Dict[str, Assignment]   # pod key -> assignment
    committed: Set[str] = field(default_factory=set)
    score: float = 0.0


class PodGroupRegistry:
    def __init__(self, cache: ClusterCache, plan_ttl_s: float = 120.0) -> None:
        self.cache = cache
        self.plan_ttl_s = plan_ttl_s
        self._lock = threading.RLock()
        self._plans: Dict[str, GangPlan] = {}
        # keys whose bind verb is between reservation-check and durable
        # commit RIGHT NOW: drop_plan must not forget their reservations —
        # the in-flight bind will land a durable annotation, and freeing
        # the chips under it would let another pod double-claim them for
        # a conflict-sweep-length window
        self._binding: Set[str] = set()
        # gang key -> incarnation id -> member keys ever seen Succeeded.
        # Completed members owe no replacement, so they shrink BOTH the
        # planner's "all members created" requirement and the stranded
        # sweep's denominator — and the memory must survive their GC
        # deletion (a TTL controller removes them between LISTs).  One
        # shared record keeps planner and sweep from ever disagreeing on
        # gang arithmetic.  Scoped by the POD_GROUP_UID annotation
        # (incarnation id, e.g. the owning Job's UID; "" when unset): a
        # NEW run reusing a gang name starts clean instead of inheriting
        # the old run's done count (ADVICE r3 medium — inherited memory
        # pinned outstanding at 0 and wedged the gang until restart).
        # (Lost on scheduler restart; the no-progress grace window is the
        # remaining protection then.)
        self._done: Dict[str, Dict[str, Set[str]]] = {}

    # -- completed-member memory ------------------------------------------
    def note_done(self, gk: str, member_key: str, incarnation: str = "") -> None:
        with self._lock:
            self._done.setdefault(gk, {}).setdefault(incarnation, set()).add(
                member_key
            )

    def note_live(self, gk: str, member_key: str) -> None:
        """A live pod reusing a remembered name must not double-count
        (once live, once as remembered-done) — in ANY incarnation: a
        recreated name supersedes every older memory of it."""
        with self._lock:
            for members in self._done.get(gk, {}).values():
                members.discard(member_key)

    def done_count(self, gk: str, incarnation: str = "") -> int:
        with self._lock:
            return len(self._done.get(gk, {}).get(incarnation, ()))

    def gang_arithmetic(
        self, gk: str, size: int, n_live: int, incarnations: List[str]
    ) -> Tuple[int, bool]:
        """(outstanding, suspect) — the ONE formula the planner
        (try_plan/planned_members) and the stranded-gang sweep share, so
        their gang arithmetic can never diverge: outstanding = the
        declared size minus every member of THIS incarnation remembered
        Succeeded (work done, no replacement owed).

        ``incarnations`` is the live members' pod-group-uids; the judged
        incarnation is their consensus, computed HERE so planner and
        sweep cannot derive it differently (ADVICE r4: the planner used
        to pass the triggering pod's own uid while the sweep took the
        consensus over all live members — with mixed-uid members during
        a name-reuse transition the two could judge different
        incarnations and disagree).

        `suspect` flags over-subscription: MORE live (non-terminal)
        members than the arithmetic leaves room for.  With incarnation
        ids that only happens in pathological flows; without them it is
        the signature of a gang name reused by a new run while the old
        run's Succeeded pods are still listed or remembered — the done
        memory belongs to the old run, and judging the new one by it
        would pin outstanding at 0 and make _select_members reject every
        member forever (the gang wedges until scheduler restart).  On
        suspicion the planner falls back to the FULL declared size (a
        forming run must WAIT for all members, never plan a premature
        sub-gang), and the sweep declines to roll anything back (the
        arithmetic is ambiguous; deleting running pods on ambiguity is
        the one unacceptable direction)."""
        done = self.done_count(gk, consensus_str(incarnations))
        out = size - done
        suspect = n_live > out
        if suspect:
            out = size
        return out, suspect

    def prune_done(self, live_gangs) -> None:
        """Forget gangs no longer listed at all (fully GC'd): nothing is
        left to judge, and a later gang reusing the name starts clean."""
        with self._lock:
            self._done = {
                gk: v for gk, v in self._done.items() if gk in live_gangs
            }

    @staticmethod
    def group_key(pod: PodInfo) -> Optional[str]:
        if not pod.pod_group:
            return None
        return f"{pod.namespace}/{pod.pod_group}"

    # -- plan lifecycle ---------------------------------------------------
    def plan_for(self, pod: PodInfo, now: Optional[float] = None) -> Optional[GangPlan]:
        """Return a live plan covering this pod, if any (expiring stale
        plans on the way)."""
        gk = self.group_key(pod)
        if gk is None:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            plan = self._plans.get(gk)
            if plan is None:
                return None
            if now - plan.created > self.plan_ttl_s and len(plan.committed) < len(plan.per_pod):
                self._expire(gk, plan)
                return None
            return plan if pod.key in plan.per_pod else None

    def _expire(self, gk: str, plan: GangPlan) -> None:
        log.warning(
            "gang plan %s expired with %d/%d committed; returning reservations",
            gk,
            len(plan.committed),
            len(plan.per_pod),
        )
        for key in plan.per_pod:
            if key not in plan.committed and key not in self._binding:
                # same mid-bind guard as drop_plan: a member whose durable
                # commit is in flight keeps its reservation (its bind
                # confirms or forgets it)
                self.cache.forget(key)
        del self._plans[gk]

    def plans_snapshot(self) -> Dict[str, dict]:
        """Locked, JSON-ready view of the in-flight plans (observability)."""
        with self._lock:
            return {
                gk: {
                    "members": sorted(p.per_pod),
                    "committed": sorted(p.committed),
                    "score": round(p.score, 1),
                }
                for gk, p in self._plans.items()
            }

    def mark_binding(self, key: str) -> None:
        with self._lock:
            self._binding.add(key)

    def unmark_binding(self, key: str) -> None:
        with self._lock:
            self._binding.discard(key)

    def drop_plan(self, gk: str) -> None:
        with self._lock:
            plan = self._plans.pop(gk, None)
            if plan:
                for key in plan.per_pod:
                    if key not in plan.committed and key not in self._binding:
                        # mid-bind members keep their reservation: their
                        # bind confirms it (success) or forgets it itself
                        # (failure path handles the planless case)
                        self.cache.forget(key)

    def reconcile(self, listed_keys, get_pod) -> None:
        """Resync backstop for MISSED DELETED events (the pod watch is the
        fast path, but a watch race can skip one): a plan covering an
        uncommitted member that is positively gone can never complete —
        it only shields its gang from re-planning and holds reservations
        until TTL.  Same discipline as the cache's reconciliation: the
        (stale) LIST only nominates; a fresh per-pod GET confirms before
        anything is dropped."""
        with self._lock:
            suspects = [
                (gk, key, plan)
                for gk, plan in self._plans.items()
                for key in plan.per_pod
                if key not in plan.committed and key not in listed_keys
            ]
        for gk, key, plan in suspects:
            ns, name = key.split("/", 1)
            try:
                get_pod(ns, name)
                continue  # exists — the LIST was just stale
            except NotFound:
                pass
            except Exception:  # noqa: BLE001 - transient: next resync retries
                continue
            with self._lock:
                # the GETs run unlocked (network): a concurrent filter may
                # have re-planned the gang meanwhile — tearing down THAT
                # fresh plan would spuriously roll back a healthy
                # admission.  Only the exact plan that nominated the
                # vanished member may be dropped.
                cur = self._plans.get(gk)
                if cur is not plan or key in cur.committed:
                    continue
                log.warning(
                    "dropping gang plan %s: planned member %s is gone and "
                    "its DELETED event was never seen (watch race); the "
                    "gang re-plans its remainder",
                    gk, key,
                )
                self.drop_plan(gk)

    def has_live_plan(self, gk: str, now: Optional[float] = None) -> bool:
        """True iff an unexpired plan covers the gang — members are still
        actively binding (the stranded-gang sweep must not count these
        resyncs as stalled)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            plan = self._plans.get(gk)
            if plan is None:
                return False
            if now - plan.created > self.plan_ttl_s and len(plan.committed) < len(plan.per_pod):
                self._expire(gk, plan)
                return False
            return True

    def try_plan(self, pod: PodInfo, now: Optional[float] = None) -> "PlanOutcome":
        """Gather the group, fit it, reserve it.  Called from filter when no
        live plan covers the pod.

        The (blocking) API-server LIST happens *before* the registry lock is
        taken — one slow list must not stall every other gang's verbs — and
        the plan covers only the group's still-unscheduled members, so a
        partially-bound gang (or a deleted-and-recreated member) re-plans
        the remainder instead of deadlocking on its own bound members."""
        gk = self.group_key(pod)
        assert gk is not None
        pending, scheduled, sched_slices, sched_coords = self._gather_members(pod)
        with self._lock:
            existing = self.plan_for(pod, now=now)
            if existing:
                return PlanOutcome(plan=existing)
            # Succeeded members owe no replacement: the outstanding size is
            # the declared size minus every member of this incarnation ever
            # seen Succeeded — matching the stranded sweep's denominator,
            # so a gang the sweep judges healthy can always re-plan its
            # remainder.  (gang_arithmetic also guards gang-name reuse by
            # a new run, which would otherwise wedge at outstanding=0.)
            outstanding, _ = self.gang_arithmetic(
                gk,
                pod.pod_group_size,
                len(pending) + len(scheduled),
                _incarnations_of(pod, pending, scheduled),
            )
            if len(pending) + len(scheduled) < outstanding:
                return PlanOutcome(
                    reason=(
                        f"gang {gk}: waiting for members "
                        f"({len(pending) + len(scheduled)}/{outstanding} "
                        "outstanding created)"
                    )
                )
            members = self._select_members(pod, pending, scheduled, outstanding)
            if members is None:
                # deterministic membership: first N by name; this pod lost
                return PlanOutcome(
                    reason=f"gang {gk}: pod {pod.key} not in first {pod.pod_group_size} members"
                )
            # fit on the best slice — or, for opted-in gangs no single slice
            # holds, across DCN-connected slices (grpalloc.multislice); cache
            # lock held through reserve so the view cannot go stale under us
            with self.cache.lock:
                views = self.cache.views()
                if pod.slice_selector is not None:
                    # tenant pinning: planning only ever sees the allowed
                    # slices (the selector is gang-wide — members share it
                    # via the same annotation)
                    views = {
                        sid: v
                        for sid, v in views.items()
                        if sid in pod.slice_selector
                    }
                    if not views:
                        return PlanOutcome(
                            reason=(
                                f"gang {gk}: no advertised slice matches "
                                f"slice-selector {sorted(pod.slice_selector)}"
                            )
                        )
                layout, occupied = fold_layout(sched_slices, sched_coords)
                # Anchored-refit math assumes every scheduled CHIP member is
                # counted in the layout.  A member whose slice cannot be
                # recovered (assignment annotation cleared mid-eviction, no
                # cache reservation) would silently undercount — and when
                # ALL scheduled members are unrecoverable (scheduler restart
                # mid-gang-eviction) the layout is empty and a fresh plan
                # would bind replacements to arbitrary slices, diverging
                # from the still-Terminating siblings' baked-in env.  Fail
                # with the real reason in both cases so the operator sees
                # why replacements are not being placed.
                lost = sorted(
                    p.key
                    for p in scheduled
                    if sched_slices.get(p.key) is None
                    and TpuRequest.from_pod(p).total_chips > 0
                )
                if lost:
                    return PlanOutcome(
                        reason=(
                            f"gang {gk}: scheduled member(s) "
                            f"{', '.join(lost)} have no recoverable "
                            "slice (assignment cleared mid-eviction?); "
                            "replacements wait until they fully disappear"
                        )
                    )
                if layout:
                    # partially-bound gang: replacements must rejoin the
                    # existing slice layout — the running siblings'
                    # rendezvous/megascale env is already baked in — and,
                    # where the survivors' coords are recoverable, restore
                    # the gang's rectangular union (exact-hole refit)
                    g = fit_gang_into_layout(views, members, layout, occupied)
                else:
                    g = fit_gang_multislice(
                        views, members, allow_multislice=pod.allow_multislice
                    )
                if not g.success:
                    return PlanOutcome(
                        reason=f"gang {gk} does not fit: {g.reason}",
                        capacity_failure=bool(views),
                    )
                taken = []
                for key, a in g.per_pod.items():
                    if not a.all_chips():
                        # chipless member (coordinator/sidecar): nothing to
                        # reserve, and it binds plain — outside the plan
                        continue
                    try:
                        self.cache.assume(key, a)
                        taken.append(key)
                    except (ValueError, KeyError) as e:
                        for k2 in taken:
                            self.cache.forget(k2)
                        return PlanOutcome(reason=f"gang {gk} reservation race: {e}")
            plan = GangPlan(
                group=gk,
                created=time.monotonic() if now is None else now,
                per_pod={k: a for k, a in g.per_pod.items() if a.all_chips()},
                score=g.score,
            )
            # NOTE on overwriting an existing plan for this gang (one that
            # did not cover this pod): its uncommitted members with LIVE
            # reservations were counted as scheduled above and keep them —
            # they bind via the assignment_of fallback.  Members that are
            # GONE (missed DELETED event) are handled by reconcile() at
            # resync with a GET-confirm; forgetting here by set-difference
            # would free chips a mid-bind member still relies on.
            self._plans[gk] = plan
            log.info(
                "gang %s planned on slice(s) %s score=%.1f%s",
                gk,
                ",".join(g.slice_ids),
                g.score,
                f" multislice shape={g.slice_shape}" if g.num_slices > 1 else "",
            )
            return PlanOutcome(plan=plan)

    @staticmethod
    def _select_members(
        pod: PodInfo, pending, scheduled, outstanding: int
    ) -> Optional[List[PodInfo]]:
        """The single source of truth for which pending pods a plan covers:
        first (outstanding - already_scheduled) by name; None if this pod
        is not among them."""
        want = outstanding - len(scheduled)
        members = sorted(pending, key=lambda p: p.key)[:want]
        if pod.key not in {p.key for p in members}:
            return None
        return members

    def layout_and_occupancy_of(self, pod: PodInfo):
        """(layout counts, occupied coords per slice) of the pod's gang —
        the full anchored-refit inputs, so preemption can simulate exactly
        the fit_gang_into_layout call try_plan will make after eviction.
        Performs a (blocking) pod LIST; call it OUTSIDE the cache lock."""
        _, _, sched_slices, sched_coords = self._gather_members(pod)
        return fold_layout(sched_slices, sched_coords)

    def planned_members(self, pod: PodInfo) -> Optional[List[PodInfo]]:
        """The member set try_plan would plan for this pod right now (used
        by preemption simulation so it can never diverge from planning)."""
        pending, scheduled, _, _ = self._gather_members(pod)
        outstanding, _ = self.gang_arithmetic(
            self.group_key(pod),
            pod.pod_group_size,
            len(pending) + len(scheduled),
            _incarnations_of(pod, pending, scheduled),
        )
        if len(pending) + len(scheduled) < outstanding:
            return None
        return self._select_members(pod, pending, scheduled, outstanding)

    def _gather_members(self, pod: PodInfo):
        """Group members split into (pending, already_scheduled), plus each
        scheduled member's bind-time slice (pod annotation, else cache
        reservation) so a re-plan can anchor replacements to the gang's
        existing slice layout.  A member is scheduled if it is bound
        (spec.nodeName) or holds a reservation in the cache — those keep
        their chips and are NOT re-planned."""
        pending = {}
        scheduled = {}
        seen = {}
        slices = {}
        coords = {}
        for obj in self.cache.api.list_pods(namespace=pod.namespace):
            try:
                p = annotations.pod_from_k8s(obj)
            except Exception:  # noqa: BLE001
                # strict parse failed.  An ALREADY-PLACED sibling must stay
                # VISIBLE (lenient) or the running gang wedges; but a
                # PENDING sibling with a malformed quantity must stay
                # INVISIBLE — it can never pass its own strict filter, so
                # planning around it (as a 0-chip ghost) would bind the
                # rest of the gang and strand those chips forever.
                try:
                    p = annotations.pod_from_k8s(obj, strict=False)
                except Exception:  # noqa: BLE001 - hopeless neighbour
                    continue
                meta = obj.get("metadata", {}) or {}
                placed = bool((obj.get("spec") or {}).get("nodeName")) or bool(
                    (meta.get("annotations") or {}).get(annotations.POD_ASSIGNMENT)
                )
                if not placed:
                    continue
            if p.pod_group != pod.pod_group:
                continue
            if p.terminal:
                # Succeeded/Failed: uncharged (ClusterCache treats terminal
                # pods as holding nothing) — neither a pending member to
                # plan nor a scheduled member anchoring slices.  Succeeded
                # additionally shrinks the outstanding size (work done, no
                # replacement owed); Failed still owes one.
                if p.phase == "Succeeded":
                    self.note_done(
                        self.group_key(pod), p.key, p.pod_group_uid
                    )
                continue
            self.note_live(self.group_key(pod), p.key)
            seen[p.key] = p
            a = annotations.assignment_from_pod(obj)
            if a is not None and a.slice_id and a.all_chips():
                slices[p.key] = a.slice_id
                coords[p.key] = frozenset(c.coords for c in a.all_chips())
        seen.setdefault(pod.key, pod)
        for key, p in seen.items():
            ca = self.cache.assignment_of(key)
            if ca is not None and ca.slice_id and ca.all_chips():
                slices.setdefault(key, ca.slice_id)
                coords.setdefault(
                    key, frozenset(c.coords for c in ca.all_chips())
                )
            if p.node_name or (key != pod.key and ca is not None):
                scheduled[key] = p
            else:
                pending[key] = p
        sched_slices = {k: slices.get(k) for k in scheduled}
        sched_coords = {k: coords[k] for k in scheduled if k in coords}
        return (
            list(pending.values()),
            list(scheduled.values()),
            sched_slices,
            sched_coords,
        )

    def mark_committed(self, pod_key: str, group_key: str) -> None:
        with self._lock:
            plan = self._plans.get(group_key)
            if plan and pod_key in plan.per_pod:
                plan.committed.add(pod_key)
                if plan.committed >= set(plan.per_pod):
                    # fully bound: the plan has served its purpose; durable
                    # state lives in pod annotations now.  Keeping it would
                    # leak memory and hand stale placements to same-named
                    # recreated pods.
                    del self._plans[group_key]

    def on_pod_deleted(self, pod: PodInfo) -> None:
        gk = self.group_key(pod)
        if gk is None:
            return
        with self._lock:
            plan = self._plans.get(gk)
            if plan and pod.key in plan.per_pod and pod.key not in plan.committed:
                # a member died before binding: the gang cannot complete
                self.drop_plan(gk)


@dataclass
class PlanOutcome:
    plan: Optional[GangPlan] = None
    reason: str = ""
    # capacity-shaped failure (preemption could fix it); never probe reason
    # strings for this
    capacity_failure: bool = False
