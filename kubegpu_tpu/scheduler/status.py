"""Operator status CLI: one readable screen of what the scheduler knows.

    python -m kubegpu_tpu.scheduler.status --url http://localhost:12345

Renders the extender's /state (slice occupancy maps, in-flight gang plans)
and the headline /metrics counters — the `kubectl get`-style surface for
the device-scheduling layer (SURVEY.md §5.5's observability row, operator
side).  Read-only; works against any live extender.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def fetch(url: str, path: str, timeout: float):
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        body = resp.read().decode()
    return json.loads(body) if path != "/metrics" else body


def render_slice(sid: str, s: dict) -> str:
    mesh = s["mesh"]
    used = {tuple(c) for c in s["used"]}
    free = {tuple(c) for c in s["free"]}
    dims = "x".join(str(d) for d in mesh)
    lines = [f"slice {sid}  mesh {dims}  "
             f"free {len(free)}  used {len(used)}  hosts {len(s['hosts'])}"]

    def cell(c):
        return "#" if c in used else "." if c in free else "x"

    if len(mesh) == 2:
        for y in range(mesh[1]):
            lines.append("  " + " ".join(cell((x, y)) for x in range(mesh[0])))
    elif len(mesh) == 3:
        # one 2D map per z-layer (v4/v5p 3D torus topologies)
        for z in range(mesh[2]):
            lines.append(f"  z={z}:")
            for y in range(mesh[1]):
                lines.append(
                    "    " + " ".join(cell((x, y, z)) for x in range(mesh[0]))
                )
    else:  # exotic rank: fall back to a coordinate listing
        lines.append(f"  used: {sorted(used)}")
        lines.append(f"  free: {sorted(free)}")
    lines.append("  (# used, . free, x unhealthy/absent)")
    return "\n".join(lines)


HEADLINE_METRICS = (
    "kubegpu_placements_total",
    "kubegpu_placements_contiguous_total",
    "kubegpu_chips_allocated_total",
    "kubegpu_preemptions_total",
    "kubegpu_preempted_pods_total",
    "kubegpu_health_evictions_total",
    "kubegpu_stranded_gang_rollbacks_total",
    "kubegpu_bind_conflicts_total",
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default="http://127.0.0.1:12345")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--json", action="store_true",
                    help="raw /state JSON instead of the rendered screen")
    args = ap.parse_args(argv)
    url = args.url.rstrip("/")

    try:
        state = fetch(url, "/state", args.timeout)
    except (OSError, ValueError) as e:
        # ValueError covers JSONDecodeError: a 200 from something that is
        # not an extender (proxy error page, wrong port) exits cleanly too
        print(f"cannot reach extender at {url}: {e}", file=sys.stderr)
        return 1

    if args.json:
        print(json.dumps(state, indent=2))
        return 0

    try:
        metrics_text = fetch(url, "/metrics", args.timeout)
    except (OSError, ValueError):
        metrics_text = ""  # render what we have; counters are optional

    print(f"extender {url}  nodes={len(state.get('nodes', []))}")
    for sid in sorted(state.get("slices", {})):
        print()
        print(render_slice(sid, state["slices"][sid]))

    plans = state.get("gang_plans", {})
    if plans:
        print("\nin-flight gang plans:")
        for gk in sorted(plans):
            p = plans[gk]
            print(f"  {gk}: {len(p['committed'])}/{len(p['members'])} "
                  f"committed, score {p['score']}")
    assumed = state.get("assumed", [])
    if assumed:
        print(f"\nassumed (reserved, bind pending): {', '.join(assumed)}")

    counters = {}
    for line in metrics_text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        if name in HEADLINE_METRICS:
            counters[name] = value
    if counters:
        print("\ncounters:")
        for name in HEADLINE_METRICS:
            if name in counters:
                print(f"  {name.removeprefix('kubegpu_'):38s} {counters[name]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
