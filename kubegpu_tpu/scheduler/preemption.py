"""Priority preemption: make room for a higher-priority job (BASELINE
config 5: multi-tenant bin-packing + preemption on a v5e-16).

The reference had no preemption (SURVEY.md §7 build-plan delta); the north
star's multi-tenant config requires it.  Semantics:

- Victims are chosen in **units**: a gang is evicted whole or not at all
  (evicting one member of a data-parallel job kills the job anyway — taking
  half its chips would strand the rest).
- Only units whose priority is strictly lower than the incoming pod's are
  candidates; least-valuable (lowest priority, then fewest chips) first.
- Selection is simulate-then-minimize: greedily free units until the
  incoming gang fits, then drop any unit whose chips turn out not to be
  needed.  Pure function over cache state — the Scheduler executes the
  eviction through the API server.
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubegpu_tpu.grpalloc import fit_gang
from kubegpu_tpu.grpalloc.view import SliceView
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.info import Assignment, PodInfo

log = logging.getLogger(__name__)


@dataclass
class VictimUnit:
    """One evictable unit: a whole gang, or a single non-gang pod."""

    unit_id: str                  # gang key or pod key
    priority: int
    pod_keys: List[str] = field(default_factory=list)
    coords_by_slice: Dict[str, Set[Tuple[int, ...]]] = field(default_factory=dict)
    # pod key -> uid, threaded through to eviction events so they attach
    # without a per-victim GET round-trip
    uids: Dict[str, str] = field(default_factory=dict)
    # newest member's durable-bind time (epoch s, from the assignment
    # annotation — survives scheduler restarts); 0.0 when unknown.  Drives
    # the min-runtime anti-starvation shield: a gang's admission completes
    # with its LAST member, so the shield window starts there.
    last_bound_at: float = 0.0

    @property
    def total_chips(self) -> int:
        return sum(len(c) for c in self.coords_by_slice.values())


@dataclass
class PreemptionDecision:
    slice_id: str
    victims: List[VictimUnit]

    def victim_pod_keys(self) -> List[str]:
        out: List[str] = []
        for v in self.victims:
            out.extend(v.pod_keys)
        return sorted(out)


def collect_units(pods_raw: Sequence[dict], assignments: Dict[str, Assignment]) -> List[VictimUnit]:
    """Group currently-assigned pods into eviction units (gangs whole)."""
    units: Dict[str, VictimUnit] = {}
    for obj in pods_raw:
        try:
            # lenient: an already-bound pod's chips must stay reclaimable
            # even if one of its quantities no longer parses
            pod = annotations.pod_from_k8s(obj, strict=False)
        except Exception:  # noqa: BLE001 - unparseable pods aren't candidates
            continue
        a = assignments.get(pod.key)
        if a is None or not a.all_chips():
            continue
        unit_id = f"gang:{pod.namespace}/{pod.pod_group}" if pod.pod_group else f"pod:{pod.key}"
        u = units.get(unit_id)
        if u is None:
            u = VictimUnit(unit_id=unit_id, priority=pod.priority)
            units[unit_id] = u
        # a unit is as valuable as its most valuable member
        u.priority = max(u.priority, pod.priority)
        u.pod_keys.append(pod.key)
        u.uids[pod.key] = pod.uid
        u.last_bound_at = max(u.last_bound_at, a.bound_at)
        if a.slice_id:
            u.coords_by_slice.setdefault(a.slice_id, set()).update(
                c.coords for c in a.all_chips()
            )
    return list(units.values())


def find_victims(
    views: Dict[str, SliceView],
    units: Sequence[VictimUnit],
    incoming: Sequence[PodInfo],
    incoming_priority: int,
    allowed_slices: Optional[Set[str]] = None,
) -> Optional[PreemptionDecision]:
    """Smallest least-valuable victim set that lets `incoming` fit on some
    slice; None if no such set exists.

    allowed_slices restricts the search to slices the scheduler's candidate
    node list can actually reach — evicting victims on a slice whose nodes
    were excluded by earlier predicates would kill workloads for zero
    benefit."""
    candidates = sorted(
        (u for u in units if u.priority < incoming_priority),
        key=lambda u: (u.priority, u.total_chips, u.unit_id),
    )
    best: Optional[PreemptionDecision] = None
    for sid in sorted(views):
        if allowed_slices is not None and sid not in allowed_slices:
            continue
        view = views[sid]
        usable = [u for u in candidates if sid in u.coords_by_slice]
        chosen: List[VictimUnit] = []
        freed: Set[Tuple[int, ...]] = set()

        def fits() -> bool:
            trial = dataclasses.replace(view, used=frozenset(view.used - freed))
            return fit_gang(trial, incoming).success

        if fits():
            # fits without preemption; caller shouldn't be here, but answer
            # honestly: no victims needed
            return PreemptionDecision(slice_id=sid, victims=[])
        for u in usable:
            chosen.append(u)
            freed |= u.coords_by_slice[sid]
            if fits():
                break
        else:
            continue  # this slice can't be freed enough
        # minimize: drop most-valuable-first any unit not actually needed
        for u in sorted(chosen, key=lambda u: (-u.priority, -u.total_chips)):
            trial_freed = freed - u.coords_by_slice[sid]
            trial = dataclasses.replace(view, used=frozenset(view.used - trial_freed))
            if fit_gang(trial, incoming).success:
                chosen.remove(u)
                freed = trial_freed
        decision = PreemptionDecision(slice_id=sid, victims=chosen)
        cost = (max((u.priority for u in chosen), default=-1), sum(u.total_chips for u in chosen))
        if best is None or cost < (
            max((u.priority for u in best.victims), default=-1),
            sum(u.total_chips for u in best.victims),
        ):
            best = decision
    return best


def find_victims_joint(
    views: Dict[str, SliceView],
    units: Sequence[VictimUnit],
    incoming_priority: int,
    fits,
    allowed_slices: Optional[Set[str]] = None,
) -> Optional[PreemptionDecision]:
    """Joint CROSS-SLICE victim search for layout-shaped requests — the
    per-slice :func:`find_victims` cannot model a gang that needs chips
    freed on several slices at once (a fresh multislice gang, or an
    anchored multislice gang refilling per-slice deficits).

    ``fits(trial_views) -> bool`` judges a hypothetical eviction (e.g.
    ``fit_gang_into_layout(...).success``); victims accumulate least-
    valuable-first across ALL allowed slices until it holds, then the set
    is minimized most-valuable-first.  A victim unit may itself span
    slices (multislice gangs evict whole)."""
    def trial(freed_by_slice: Dict[str, Set[Tuple[int, ...]]]):
        return {
            sid: (
                dataclasses.replace(
                    v, used=frozenset(v.used - freed_by_slice[sid])
                )
                if sid in freed_by_slice
                else v
            )
            for sid, v in views.items()
        }

    def freeable(u: VictimUnit) -> Dict[str, Set[Tuple[int, ...]]]:
        return {
            sid: set(cs)
            for sid, cs in u.coords_by_slice.items()
            if allowed_slices is None or sid in allowed_slices
        }

    candidates = sorted(
        (
            u
            for u in units
            if u.priority < incoming_priority and freeable(u)
        ),
        key=lambda u: (u.priority, u.total_chips, u.unit_id),
    )
    if fits(trial({})):
        return PreemptionDecision(slice_id="", victims=[])
    freed: Dict[str, Set[Tuple[int, ...]]] = {}
    chosen: List[VictimUnit] = []
    for u in candidates:
        chosen.append(u)
        for sid, cs in freeable(u).items():
            freed.setdefault(sid, set()).update(cs)
        if fits(trial(freed)):
            break
    else:
        return None
    # minimize: drop most-valuable-first any unit not actually needed
    for u in sorted(chosen, key=lambda u: (-u.priority, -u.total_chips)):
        trial_freed = {sid: set(cs) for sid, cs in freed.items()}
        for sid, cs in freeable(u).items():
            trial_freed[sid] -= cs
        if fits(trial(trial_freed)):
            chosen.remove(u)
            freed = trial_freed
    return PreemptionDecision(slice_id="", victims=chosen)
