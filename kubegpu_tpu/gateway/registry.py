"""Replica discovery: which decode replicas exist and which are routable.

The control plane already publishes everything discovery needs — the bind
writes each replica pod's chip assignment into its annotations, and the
advertiser re-publishes per-chip health into node annotations every cycle
(SURVEY.md §1: annotations ARE the durable state).  The registry is a pure
read-side join of the two:

    replica is LIVE  ⇔  pod carries kubegpu-tpu/serving-group
                     ∧  pod is bound with an assignment annotation
                     ∧  pod is not terminal / terminating
                     ∧  every assigned chip is currently advertised healthy

so a chip death drains its replica in the SAME advertise cycle the
scheduler sees it — no separate health prober, no lag between "scheduler
evicts the pod" and "gateway stops routing to it".  Watches (node + pod)
make the drain event-driven; a periodic refresh is the consistency
backstop, exactly the scheduler's informer/resync split.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Set

from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.topology import Coord
from kubegpu_tpu.utils.apiserver import ApiServer

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class ReplicaInfo:
    """One routable decode replica (a bound serving pod)."""

    key: str                      # "namespace/pod" — the routing identity
    pod: str
    namespace: str
    group: str
    node: str
    slice_id: Optional[str]
    coords: FrozenSet[Coord] = field(default_factory=frozenset)
    healthy: bool = True
    reason: str = ""              # why not healthy (operator-facing)
    # data-plane address (status.podIP): where the replica's HTTP serving
    # endpoint lives — the HttpReplicaClient resolves "addr:port" from
    # this.  None until kubelet reports the IP (the HTTP probe then
    # holds the replica out of the live set, which is correct: a replica
    # without a routable address cannot serve)
    addr: Optional[str] = None
    # DRAINING: still healthy and serving its in-flight work (the data
    # plane keeps its connections), but the router must not place new
    # admissions on it — the graceful half of the replica lifecycle:
    # DRAINING → (sessions migrated / sealed exports captured) → released
    draining: bool = False
    # serving ROLE in a disaggregated fleet (POD_ROLE annotation):
    # "prefill" | "decode" | "flex".  The router prefers prefill replicas
    # for fresh admissions and keeps new work OFF pure-prefill replicas'
    # decode path; absent annotation = flex (co-located, the default)
    role: str = "flex"


class ReplicaRegistry:
    """Read-side join of pod assignments and advertiser health.

    ``subscribe`` observers fire on every live-set CHANGE with the new
    frozenset of live replica keys — the gateway uses this to fail over
    in-flight work the moment a replica drains, and the in-memory replica
    client uses it to model the pod's process dying with its chips.
    """

    def __init__(self, api: ApiServer, group: Optional[str] = None,
                 probe=None, clock: Optional[Callable[[], float]] = None,
                 probe_backoff_base_s: float = 0.5,
                 probe_backoff_cap_s: float = 30.0) -> None:
        self.api = api
        self.group = group  # None = every serving group
        # optional DATA-PLANE health probe: called with each replica the
        # annotation join believes healthy; (False, why) drains it.  The
        # HTTP data plane wires HttpReplicaClient.probe here so in-cluster
        # liveness is the serving endpoint answering /healthz, not just
        # the control plane's chip-health join.  Probes run serially
        # inside the refresh (bounded ~1 s timeout each) — fine for the
        # replica counts a gateway fronts; sample or parallelize before
        # pointing this at hundreds of replicas.
        self.probe = probe
        # probe BACKOFF: a failing replica's probe retries exponentially
        # (base * 2^(fails-1), capped) with per-try jitter in [0.5, 1.5)x
        # instead of re-probing a dead address every refresh cycle — with
        # watches coalescing refreshes, a dead pod would otherwise eat a
        # connect timeout per cluster event.  A probe success resets the
        # backoff; the clock is injectable for fake-clock unit tests.
        self._clock = clock if clock is not None else time.monotonic
        self.probe_backoff_base_s = probe_backoff_base_s
        self.probe_backoff_cap_s = probe_backoff_cap_s
        self._backoff_rng = random.Random(0)
        # key -> {"fails", "next", "why"}; guarded by _refresh_lock (the
        # only writer is the refresh cycle)
        self._probe_backoff: Dict[str, dict] = {}
        # keys an operator (or Gateway.drain_replica) marked DRAINING
        self._draining: Set[str] = set()
        self._lock = threading.Lock()
        # serializes whole refresh cycles (LIST → join → swap): the watch
        # handlers and the periodic loop both call refresh(), and an older
        # cycle's snapshot must not land AFTER a newer one resurrected —
        # briefly — a replica the newer cycle had drained
        self._refresh_lock = threading.Lock()
        self._replicas: Dict[str, ReplicaInfo] = {}
        self._observers: List[Callable[[FrozenSet[str]], None]] = []
        self._last_live: FrozenSet[str] = frozenset()
        # watch-event coalescing: with a refresher thread running, event
        # handlers set this flag instead of refreshing inline — an
        # advertise cycle over N nodes folds into ~one refresh, not N
        # serialized full LISTs
        self._dirty = threading.Event()
        self._refresher_running = False

    # -- discovery ---------------------------------------------------------
    def refresh(self) -> None:
        """Full LIST resync: rebuild the replica table from pod + node
        annotations.  Cheap (one pod LIST + one node LIST) and idempotent;
        the watch handlers call it too, so event and resync paths cannot
        diverge."""
        with self._refresh_lock:
            self._refresh_locked()

    def _probe_with_backoff(self, key: str, info: "ReplicaInfo"):
        """One backoff-gated probe attempt.  Inside the backoff window
        the cached failure stands (no network touch); outside it the
        probe runs — success clears the state, failure doubles the
        window (jittered, capped)."""
        now = self._clock()
        st = self._probe_backoff.get(key)
        if st is not None and now < st["next"]:
            return False, (
                f"{st['why']} (probe backing off, {st['fails']} fails)"
            )
        ok, why = self.probe(info)
        if ok:
            self._probe_backoff.pop(key, None)
            return True, ""
        fails = (st["fails"] + 1) if st is not None else 1
        delay = min(
            self.probe_backoff_cap_s,
            self.probe_backoff_base_s * 2 ** (fails - 1),
        ) * (0.5 + self._backoff_rng.random())
        self._probe_backoff[key] = {
            "fails": fails, "next": now + delay, "why": why,
        }
        return False, why

    def _refresh_locked(self) -> None:
        chip_health: Dict[tuple, bool] = {}
        advertised_slices = set()
        for node_obj in self.api.list_nodes():
            info = annotations.node_from_k8s(node_obj)
            if info.slice_id is None:
                continue
            advertised_slices.add(info.slice_id)
            for ch in info.chips:
                chip_health[(info.slice_id, ch.coords)] = ch.healthy

        with self._lock:
            draining = set(self._draining)

        replicas: Dict[str, ReplicaInfo] = {}
        for obj in self.api.list_pods():
            meta = obj.get("metadata", {}) or {}
            ann = dict(meta.get("annotations") or {})
            group = ann.get(annotations.POD_SERVING_GROUP)
            if not group or (self.group is not None and group != self.group):
                continue
            ns = meta.get("namespace", "default")
            name = meta.get("name", "")
            key = f"{ns}/{name}"
            # adopt the durable DRAINING mark: a restarted process's
            # fresh registry must not re-admit a half-drained replica
            # (the in-memory set dies with the process; the annotation
            # doesn't — and a recreated pod starts without it)
            if ann.get(annotations.POD_DRAINING) == "true":
                draining.add(key)
                with self._lock:
                    self._draining.add(key)
            node = (obj.get("spec") or {}).get("nodeName") or ""
            a = annotations.assignment_from_pod(obj)
            status = obj.get("status") or {}
            phase = (status.get("phase") or "")
            addr = status.get("podIP") or None
            healthy, reason = True, ""
            coords: FrozenSet[Coord] = frozenset()
            slice_id = None
            if not node or a is None:
                healthy, reason = False, "unscheduled (no assignment yet)"
            elif phase in ("Succeeded", "Failed"):
                healthy, reason = False, f"terminal ({phase})"
            elif meta.get("deletionTimestamp"):
                healthy, reason = False, "terminating"
            else:
                slice_id = a.slice_id
                coords = frozenset(c.coords for c in a.all_chips())
                if slice_id is not None and slice_id not in advertised_slices:
                    healthy, reason = False, f"slice {slice_id} not advertised"
                else:
                    dead = sorted(
                        c for c in coords
                        if not chip_health.get((slice_id, c), False)
                    )
                    if dead:
                        healthy, reason = False, f"dead chips {dead}"
            role = ann.get(annotations.POD_ROLE) or "flex"
            if role not in ("prefill", "decode", "flex"):
                role = "flex"   # unknown role values degrade to co-located
            info = ReplicaInfo(
                key=key, pod=name, namespace=ns, group=group, node=node,
                slice_id=slice_id, coords=coords, healthy=healthy,
                reason=reason, addr=addr, draining=key in draining,
                role=role,
            )
            if healthy and self.probe is not None:
                ok, why = self._probe_with_backoff(key, info)
                if not ok:
                    info = ReplicaInfo(
                        key=key, pod=name, namespace=ns, group=group,
                        node=node, slice_id=slice_id, coords=coords,
                        healthy=False, reason=f"data plane: {why}",
                        addr=addr, draining=key in draining, role=role,
                    )
            replicas[key] = info

        # prune state for replicas that left the cluster: a recreated
        # pod under the same name starts with a clean slate (no stale
        # backoff window, no inherited DRAINING mark)
        self._probe_backoff = {
            k: v for k, v in self._probe_backoff.items() if k in replicas
        }
        with self._lock:
            self._draining &= set(replicas)
            self._replicas = replicas
            live = frozenset(k for k, r in replicas.items() if r.healthy)
            changed = live != self._last_live
            self._last_live = live
            observers = list(self._observers)
        if changed:
            for fn in observers:
                try:
                    fn(live)
                except Exception:  # noqa: BLE001 - observers are best-effort
                    log.exception("replica-set observer failed")

    # -- replica lifecycle (DRAINING → released) ---------------------------
    def set_draining(self, key: str, draining: bool = True) -> None:
        """Mark a replica DRAINING (or clear it).  A draining replica
        stays HEALTHY — its data-plane connections and in-flight
        sequences keep serving, and the live-set observers do NOT fire
        (firing would make clients abort the very streams a graceful
        drain is migrating) — but ``routable()`` excludes it, so no new
        admissions land there.  Refreshes immediately so routing sees
        the state this cycle."""
        with self._lock:
            if draining:
                self._draining.add(key)
            else:
                self._draining.discard(key)
        # persist the mark on the pod (best-effort): the in-memory set
        # dies with this process, and a restarted controller adopts an
        # in-progress drain from the annotation at its first refresh
        patch = getattr(self.api, "patch_pod_annotations", None)
        if patch is not None:
            ns, _, name = key.partition("/")
            try:
                patch(ns, name, {
                    annotations.POD_DRAINING: "true" if draining else "",
                })
            except Exception:  # noqa: BLE001 - the mark still holds
                log.debug("draining annotation patch failed for %s", key,
                          exc_info=True)
        self.refresh()

    def draining_keys(self) -> FrozenSet[str]:
        with self._lock:
            return frozenset(self._draining)

    def set_role(self, key: str, role: str) -> None:
        """Reassign a replica's serving role (the FleetController's
        prefill:decode ratio actuator).  Persisted on the pod like the
        DRAINING mark — a restarted gateway adopts the fleet's current
        role split from annotations at its first refresh — then
        refreshed immediately so routing sees the new role this cycle.
        In-flight sequences are untouched: a prefill→flex flip only
        changes where NEW admissions land and whether the replica's
        batcher parks post-seal."""
        if role not in ("prefill", "decode", "flex"):
            raise ValueError(f"unknown role {role!r}")
        patch = getattr(self.api, "patch_pod_annotations", None)
        if patch is not None:
            ns, _, name = key.partition("/")
            try:
                patch(ns, name, {
                    annotations.POD_ROLE: "" if role == "flex" else role,
                })
            except Exception:  # noqa: BLE001 - best-effort, like draining
                log.debug("role annotation patch failed for %s", key,
                          exc_info=True)
        self.refresh()

    # -- views -------------------------------------------------------------
    def live(self) -> List[ReplicaInfo]:
        """Healthy replicas (DRAINING included — their data plane is
        alive), name-sorted for deterministic iteration."""
        with self._lock:
            return sorted(
                (r for r in self._replicas.values() if r.healthy),
                key=lambda r: r.key,
            )

    def routable(self) -> List[ReplicaInfo]:
        """Replicas new admissions may land on: healthy AND not
        draining — the router's view of the world."""
        return [r for r in self.live() if not r.draining]

    def all(self) -> List[ReplicaInfo]:
        with self._lock:
            return sorted(self._replicas.values(), key=lambda r: r.key)

    def get(self, key: str) -> Optional[ReplicaInfo]:
        with self._lock:
            return self._replicas.get(key)

    def live_keys(self) -> FrozenSet[str]:
        with self._lock:
            return self._last_live

    def subscribe(self, fn: Callable[[FrozenSet[str]], None]) -> None:
        with self._lock:
            self._observers.append(fn)

    def unsubscribe(self, fn: Callable[[FrozenSet[str]], None]) -> None:
        """Detach an observer (idempotent).  A registry is SHARED across
        a gateway tier; a killed gateway must stop observing the live
        set — a corpse mutating the shared session store (or publishing
        gauges) on every membership change is the kind of half-dead
        process a real crash never leaves behind."""
        with self._lock:
            try:
                self._observers.remove(fn)
            except ValueError:
                pass

    # -- event plumbing ----------------------------------------------------
    def _request_refresh(self) -> None:
        """Refresh now, or mark dirty for the coalescing refresher if one
        is running (start_watches) — a burst of events then costs one
        refresh instead of one per event."""
        if self._refresher_running:
            self._dirty.set()
        else:
            self.refresh()

    def on_pod_event(self, event: str, obj: dict) -> None:
        ann = ((obj.get("metadata") or {}).get("annotations") or {})
        if annotations.POD_SERVING_GROUP in ann:
            self._request_refresh()

    def on_node_event(self, event: str, obj: dict) -> None:
        # only advertiser-annotated nodes affect replica health
        ann = ((obj.get("metadata") or {}).get("annotations") or {})
        if annotations.NODE_TOPOLOGY in ann or event == "node-deleted":
            self._request_refresh()

    def start_watches(self, stop: threading.Event) -> List[threading.Thread]:
        """Spawn the node + pod watch threads (the event-driven drain
        path) plus the coalescing refresher that serves their dirty
        flags; callers keep the periodic refresh as backstop."""
        self._refresher_running = True

        def refresher():
            while not stop.is_set():
                if not self._dirty.wait(0.05):
                    continue
                self._dirty.clear()
                try:
                    self.refresh()
                except Exception:  # noqa: BLE001
                    log.exception("coalesced refresh failed; will retry")

        threads = []
        for target in (
            lambda: self.api.watch_nodes(self.on_node_event, stop),
            lambda: self.api.watch_pods(self.on_pod_event, stop),
            refresher,
        ):
            t = threading.Thread(target=self._guard(target), daemon=True)
            t.start()
            threads.append(t)
        return threads

    @staticmethod
    def _guard(target):
        def run():
            try:
                target()
            except NotImplementedError:
                log.info("api server has no watch; relying on refresh loop")
            except Exception:  # noqa: BLE001
                log.exception("registry watch died; relying on refresh loop")
        return run
