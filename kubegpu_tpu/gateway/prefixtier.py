"""Fleet-wide shared-prefix KV tier: prefill any hot prefix once, ever.

Every replica re-prefills hot shared prefixes (system prompts, RAG
corpus chunks, agent scaffolds) once per LOCAL cache lifetime, even
though sealed chains are content-addressed (the ``PrefixPageCache``
keys are cumulative sha256 over the token stream) and the external
store already moves sealed-KV payloads for session failover.  This
module promotes the store to a GLOBAL prefix tier:

- **publish**: when a replica seals a chain (any completed request),
  the gateway exports it (``client.export_sealed`` — the existing
  ``/v1/export`` sealed twin) and publishes it to the store keyed by
  the chain's cumulative content hash.  The store deduplicates payload
  bytes by content with refcounted references, gives prefixes their own
  TTL/eviction class (immortal-while-hot, popularity-weighted LRU —
  distinct from session leases), and quantized pools ride the int8
  half-width wire for free (the payload carries its ``scales``).
- **probe + import**: at dispatch, when the routed replica is not
  already warm for the request's prompt, the tier probes the store
  METADATA-FIRST (the longest stored chain sharing a prefix with the
  prompt — a point lookup per page key, walked longest-first) and
  imports the stored chain into the replica BEFORE prefill.  A hot
  system prompt therefore prefills ONCE fleet-wide; every other
  replica's first sight of it is a KV import, not a prefill.
- **locality**: the tier keeps an advisory per-replica warmth map
  (which chains were sealed on / imported into each replica) that
  ``PrefixLocalityRouter`` scores routes by — an agent fleet sharing
  one scaffold packs onto warm replicas instead of spraying imports.

Degradation contract (same discipline as the session store): the tier
is an OPTIMIZATION, never a dependency.  Store unreachable, a probe
error, an import refusal — every failure resolves as a counted cold
prefill (``gateway_prefix_tier_degraded_total{reason}`` plus the
``degraded_log`` audit trail), NEVER a request error.  The warmth map
is advisory the same way: a stale entry costs at most one skipped probe
or one cold route, never correctness — the replica's own content-keyed
cache is the ground truth at admission.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from kubegpu_tpu.gateway.sessionstore import (
    InProcessStoreBackend,
    SessionStoreBackend,
)
from kubegpu_tpu.utils.metrics import Metrics

log = logging.getLogger(__name__)

PREFIX_DEGRADE_REASONS = ("unreachable", "error")


def prompt_chain_keys(prompt, page: int) -> List[str]:
    """Cumulative chain keys of a prompt's full pages — the EXACT
    discipline ``PagedContinuousBatcher._chain_keys`` seals under (one
    sha256 updated per int32 page window, snapshot per page), so keys
    computed gateway-side hit chains sealed replica-side.  Only full
    pages have keys; a partial tail page is never sealed and never
    probes."""
    if page <= 0:
        return []
    stream = np.asarray(list(prompt), np.int32)
    n_full = stream.shape[0] // page
    h = hashlib.sha256()
    keys: List[str] = []
    for j in range(n_full):
        h.update(stream[j * page: (j + 1) * page].tobytes())
        keys.append(h.copy().hexdigest())
    return keys


class PrefixTier:
    """The gateway-side prefix-tier engine over a ``SessionStoreBackend``
    that implements the prefix namespace (``InProcessStoreBackend`` in
    one process, ``HttpStoreClient`` against the external store — the
    same object the ``SessionKVStore`` runs over, so one store serves
    both key classes).

    ``page`` is the fleet's KV page size (the chain-hash window).  The
    tier also LEARNS per-replica page sizes from payload geometry as
    publishes/imports flow, so mixed-page fleets probe with the right
    keys once a replica has spoken.

    Publishes run asynchronously off the result path (bounded queue,
    drop-oldest, deduped by chain key — the same insurance-not-blocking
    shape as ``SessionKVStore.capture_async``)."""

    def __init__(self, backend: Optional[SessionStoreBackend] = None,
                 page: int = 8,
                 metrics: Optional[Metrics] = None,
                 max_warm_chains: int = 4096,
                 max_published: int = 8192,
                 publish_queue: int = 64) -> None:
        self.backend = backend if backend is not None else (
            InProcessStoreBackend()
        )
        self.default_page = int(page)
        self.metrics = metrics
        self.max_warm_chains = max_warm_chains
        self.max_published = max_published
        self.publish_queue = publish_queue
        self._lock = threading.Lock()
        # replica key -> chain key (hex) -> pages covered (advisory
        # warmth: sealed-here / imported-here; dropped on any replica
        # lifecycle event — stale = one cold route, never wrong tokens)
        self._warm: Dict[str, "OrderedDict[str, int]"] = {}
        # replica key -> learned page size (payload geometry)
        self._replica_page: Dict[str, int] = {}
        # chain keys this gateway already published (bounded): a hot
        # prefix's thousandth completion must not re-export megabytes
        self._published: "OrderedDict[str, bool]" = OrderedDict()
        # every degrade event in order: (op, reason) — the audit trail
        # the soak holds against the metric
        self.degraded_log: List[Tuple[str, str]] = []
        self._cond = threading.Condition()
        self._queue: deque = deque()   # (client, replica_key, stream)
        self._inflight = 0
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    # -- accounting --------------------------------------------------------
    def _degrade(self, op: str, reason: str) -> None:
        with self._lock:
            self.degraded_log.append((op, reason))
        if self.metrics is not None:
            self.metrics.inc("gateway_prefix_tier_degraded_total",
                             reason=reason)

    # -- warmth map --------------------------------------------------------
    def page_for(self, replica_key: str) -> int:
        with self._lock:
            return self._replica_page.get(replica_key, self.default_page)

    def _learn_geometry(self, replica_key: str, payload) -> None:
        page = 0
        if isinstance(payload, dict):
            page = int((payload.get("geometry") or {}).get("page") or 0)
        if page > 0:
            with self._lock:
                self._replica_page[replica_key] = page

    def note_warm(self, replica_key: str, keys: Iterable[str]) -> None:
        """Record chain keys known resident on one replica (sealed there
        or imported there).  Advisory — bounded LRU per replica."""
        with self._lock:
            warm = self._warm.setdefault(replica_key, OrderedDict())
            for i, key in enumerate(keys):
                warm[str(key)] = i + 1
                warm.move_to_end(str(key))
            while len(warm) > self.max_warm_chains:
                warm.popitem(last=False)

    def warm_pages(self, replica_key: str, keys: List[str]) -> int:
        """Longest prefix of ``keys`` (a prompt's cumulative chain keys)
        believed warm on ``replica_key``, in pages.  Cumulative hashing
        means key j warm ⇒ pages 0..j warm — one lookup per key walked
        longest-first."""
        with self._lock:
            warm = self._warm.get(replica_key)
            if not warm:
                return 0
            for j in range(len(keys) - 1, -1, -1):
                if str(keys[j]) in warm:
                    warm.move_to_end(str(keys[j]))
                    return j + 1
        return 0

    def locality_scores(self, prompt,
                        replica_keys: Iterable[str]) -> Dict[str, int]:
        """Warm-page count per replica for one prompt — the router's
        scoring input.  Chain keys are computed once per distinct page
        size, not once per replica."""
        keys_by_page: Dict[int, List[str]] = {}
        out: Dict[str, int] = {}
        for rk in replica_keys:
            page = self.page_for(rk)
            if page not in keys_by_page:
                keys_by_page[page] = prompt_chain_keys(prompt, page)
            keys = keys_by_page[page]
            out[rk] = self.warm_pages(rk, keys) if keys else 0
        return out

    def forget_replica(self, replica_key: str) -> None:
        """Replica drained/died/cold-restarted: its warmth is gone (or
        unknowable, which is the same thing for an advisory map)."""
        with self._lock:
            self._warm.pop(replica_key, None)

    def sync_live(self, live) -> None:
        live = set(live)
        with self._lock:
            for key in [k for k in self._warm if k not in live]:
                self._warm.pop(key, None)

    # -- the dispatch-path read (probe + pre-prefill import) ---------------
    def ensure_warm(self, request, replica_key: str, client) -> bool:
        """Called at dispatch with the routed target: if the target is
        not already warm for this prompt, probe the tier for the longest
        stored prefix and import it BEFORE the attempt opens — so the
        replica's admission finds the pages already cached and prefills
        only the genuinely new tail.  True only when a payload actually
        landed.  Every store failure degrades to a counted cold
        prefill, never an error."""
        prompt = getattr(request, "prompt", None)
        if not prompt:
            return False
        page = self.page_for(replica_key)
        keys = prompt_chain_keys(prompt, page)
        if not keys:
            return False
        local = self.warm_pages(replica_key, keys)
        if local >= len(keys):
            return False   # locally warm: the replica's own cache serves
        try:
            probe = self.backend.probe_prefix(keys)
        except Exception:  # noqa: BLE001 - the tier must never raise
            self._degrade("probe", "error")
            return False
        if probe.status == "unreachable":
            self._degrade("probe", "unreachable")
            return False
        if probe.status != "ok" or not probe.entry:
            if self.metrics is not None:
                self.metrics.inc("gateway_prefix_tier_misses_total")
            return False
        if self.metrics is not None:
            self.metrics.inc("gateway_prefix_tier_hits_total")
        entry = probe.entry
        chain = entry.get("chain")
        match = int(entry.get("match_pages") or 0)
        if not chain or match <= local:
            return False   # the tier holds nothing beyond local warmth
        try:
            full = self.backend.get_prefix(str(chain))
        except Exception:  # noqa: BLE001
            self._degrade("fetch", "error")
            return False
        if full.status == "unreachable":
            self._degrade("fetch", "unreachable")
            return False
        if full.status != "ok" or not full.entry:
            return False   # evicted between probe and fetch: cold
        payload = full.entry.get("payload")
        if payload is None:
            return False
        try:
            imported = bool(client.import_sealed(replica_key, payload))
        except Exception:  # noqa: BLE001 - import is best-effort
            log.exception("prefix-tier import failed")
            imported = False
        if imported:
            if self.metrics is not None:
                self.metrics.inc("gateway_prefix_tier_imports_total")
            self._learn_geometry(replica_key, payload)
            self.note_warm(
                replica_key,
                [str(k) for k in full.entry.get("page_keys") or []],
            )
        return imported

    # -- the publish hook (sealed chains -> the tier) ----------------------
    def publish(self, client, replica_key: str, stream) -> bool:
        """Export the sealed chain for ``stream`` from the replica that
        just served it and publish it under its chain key.  Metadata-
        first: a chain the store already holds costs one meta GET, not a
        payload upload (and a re-publish store-side is a popularity
        bump, never a duplicate — the payload table is content-
        addressed and refcounted).  Best-effort end to end."""
        try:
            payload = client.export_sealed(replica_key, list(stream))
        except Exception:  # noqa: BLE001 - export is best-effort
            payload = None
        if not payload:
            return False
        page_keys = [str(k) for k in payload.get("page_keys") or []]
        if not page_keys:
            return False
        self._learn_geometry(replica_key, payload)
        self.note_warm(replica_key, page_keys)  # sealed here ⇒ warm here
        chain = page_keys[-1]
        with self._lock:
            if chain in self._published:
                self._published.move_to_end(chain)
                return False
        try:
            meta = self.backend.get_prefix(chain, meta=True)
        except Exception:  # noqa: BLE001
            self._degrade("publish", "error")
            return False
        if meta.status == "unreachable":
            self._degrade("publish", "unreachable")
            return False
        if meta.status == "ok":
            self._mark_published(chain)
            return False   # a sibling already published it
        try:
            res = self.backend.put_prefix(chain, {
                "payload": payload,
                "page_keys": page_keys,
                "pages": len(page_keys),
            })
        except Exception:  # noqa: BLE001
            self._degrade("publish", "error")
            return False
        if res.status == "unreachable":
            self._degrade("publish", "unreachable")
            return False
        if res.status != "ok":
            return False
        self._mark_published(chain)
        if self.metrics is not None:
            self.metrics.inc("gateway_prefix_tier_publishes_total")
        return True

    def _mark_published(self, chain: str) -> None:
        with self._lock:
            self._published[chain] = True
            self._published.move_to_end(chain)
            while len(self._published) > self.max_published:
                self._published.popitem(last=False)

    def publish_async(self, client, replica_key: str, stream) -> None:
        """Queue a publish off the result path (bounded, drop-oldest,
        deduped).  The cheap pre-gate: when the stream's chain key (at
        the replica's learned page size) was already published by this
        gateway, skip the queue entirely — a wrong learned page size
        only skips the OPTIMIZATION (the publish itself dedups)."""
        stream = [int(t) for t in stream]
        page = self.page_for(replica_key)
        n_full = max(0, (len(stream) - 1)) // page
        if n_full < 1:
            return   # nothing sealable: no full committed page
        keys = prompt_chain_keys(stream, page)
        chain = keys[n_full - 1] if len(keys) >= n_full else None
        with self._cond:
            if self._closed:
                return
            if chain is not None and chain in self._published:
                self._published.move_to_end(chain)
                return
            for i, (_, _, queued) in enumerate(self._queue):
                if queued == stream:
                    del self._queue[i]
                    break
            self._queue.append((client, replica_key, stream))
            dropped = 0
            while len(self._queue) > self.publish_queue:
                self._queue.popleft()
                dropped += 1
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._publish_loop, name="prefix-tier-publish",
                    daemon=True,
                )
                self._worker.start()
            self._cond.notify()
        if dropped and self.metrics is not None:
            self.metrics.inc(
                "gateway_prefix_tier_publish_drops_total", dropped
            )

    def _publish_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.1)
                if self._closed and not self._queue:
                    return
                client, replica_key, stream = self._queue.popleft()
                self._inflight += 1
            try:
                self.publish(client, replica_key, stream)
            except Exception:  # noqa: BLE001 - must never raise
                log.exception("async prefix publish failed")
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def flush_publishes(self, timeout: float = 10.0) -> bool:
        """Wait for every queued publish to land (tests, drains)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- views -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "warm_replicas": len(self._warm),
                "warm_chains": sum(len(w) for w in self._warm.values()),
                "published": len(self._published),
                "degraded": len(self.degraded_log),
            }
