"""Gateway HTTP frontend (the data-plane twin of scheduler/server.py).

Thin codec over ``gateway/core.py`` — every routing/admission/failover
behavior is testable without sockets; this module only translates HTTP:

    POST /v1/generate   {"prompt": [ints], "max_new_tokens": n,
                         "tenant": "...", "session": "...",
                         "temperature": t, "deadline_s": s,
                         "stream": bool}
        → 200 {"tokens": [...], "replica": "...", "attempts": n,
               "hedged": bool}
        → 200 text/event-stream when "stream": true — committed token
          batches relayed from the replica as ``tokens`` events, then a
          terminal ``done`` (the authoritative full result) or ``error``
          event; a caller that disconnects mid-stream cancels the
          request all the way down to the replica's page pool
        → 429 {"error": ...}   explicit backpressure (queue full)
        → 502 {"error": ...}   all attempts failed
        → 504 {"error": ...}   deadline exceeded
    GET  /healthz       liveness (the process serves)
    GET  /readyz        readiness (≥1 routable — live, not draining —
                        replica to route new admissions to)
    GET  /metrics       Prometheus text (TTFT/queue-wait histograms,
                        queue-depth/live-replica gauges)
    GET  /state         debug dump (replicas, queue, outcome counts)
    GET  /debug/trace   recent completed request span trees (admission
                        wait → route → dispatch → replica-side serve
                        phases) + per-replica serving-ledger rows;
                        ?n=K caps the trace count (default 32).  See
                        README "Observability" for a worked example
                        explaining a slow TTFT from this endpoint.

Run self-hosted on a fabricated cluster for demos/tests (no k8s, no TPUs):
    python -m kubegpu_tpu.gateway.server --fake-cluster v5e-16 --replicas 3
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from kubegpu_tpu.gateway.core import Gateway, GatewayRequest
from kubegpu_tpu.gateway.queue import AdmissionQueue
from kubegpu_tpu.gateway.registry import ReplicaRegistry

log = logging.getLogger(__name__)

_STATUS_HTTP = {"ok": 200, "rejected": 429, "error": 502, "timeout": 504}


def make_handler(gateway: Gateway, registry: ReplicaRegistry):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _read_json(self) -> Optional[dict]:
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                return json.loads(raw) if raw else {}
            except (ValueError, json.JSONDecodeError):
                return None

        def _send(self, code: int, payload,
                  content_type="application/json") -> None:
            body = (
                json.dumps(payload).encode()
                if content_type == "application/json"
                else payload.encode()
            )
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if code == 429:
                # every 429 is RETRYABLE backpressure by contract —
                # queue-full, brownout shed, deadline shed-before-work —
                # and well-behaved clients honor Retry-After instead of
                # hammering a browned-out fleet
                self.send_header("Retry-After", "1")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            log.debug("http: " + fmt, *args)

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, "ok", content_type="text/plain")
            elif self.path == "/readyz":
                # ready ⇔ THIS instance is accepting (dispatcher pool
                # up, not draining — a SIGTERM'd pod must drop out of
                # the Service while it finishes its in-flight streams)
                # AND at least one replica to route NEW work to AND a
                # data plane that can reach it; any gap means a gateway
                # in the Service would eat traffic into guaranteed 5xx.
                # ROUTABLE, not live: a fleet that is entirely DRAINING
                # still serves its in-flight streams but can admit
                # nothing — the load balancer must fast-fail instead of
                # feeding requests into deadline-exceeded
                if not gateway.accepting:
                    self._send(503, "draining" if gateway.draining
                               else "not accepting (no dispatcher pool)",
                               content_type="text/plain")
                elif not gateway.client.ready():
                    self._send(503, "data plane not wired "
                               "(no replica client)",
                               content_type="text/plain")
                elif registry.routable():
                    self._send(200, "ok", content_type="text/plain")
                else:
                    self._send(503, "no routable replicas",
                               content_type="text/plain")
            elif self.path == "/metrics":
                self._send(200, gateway.metrics.render(),
                           content_type="text/plain")
            elif self.path == "/state":
                self._send(200, _debug_state(gateway, registry))
            elif self.path.split("?", 1)[0] == "/debug/trace":
                self._send(200, _debug_trace(gateway, self.path))
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/v1/generate":
                self._send(404, {"error": f"no route {self.path}"})
                return
            body = self._read_json()
            if body is None:
                self._send(400, {"error": "malformed JSON body"})
                return
            try:
                request = GatewayRequest(
                    prompt=[int(t) for t in body.get("prompt") or []],
                    max_new_tokens=int(body.get("max_new_tokens", 0)),
                    tenant=str(body.get("tenant", "")),
                    session=body.get("session"),
                    temperature=float(body.get("temperature", 0.0)),
                    seed=(
                        int(body["seed"])
                        if body.get("seed") is not None else None
                    ),
                    deadline_s=(
                        float(body["deadline_s"])
                        if body.get("deadline_s") is not None else None
                    ),
                )
            except (TypeError, ValueError) as e:
                self._send(400, {"error": f"bad request: {e}"})
                return
            if not request.prompt:
                self._send(400, {"error": "bad request: empty prompt"})
                return
            if body.get("stream"):
                try:
                    resume = max(0, int(body.get("resume_watermark", 0)))
                except (TypeError, ValueError):
                    resume = 0
                if body.get("request_id"):
                    # a RESUMED stream keeps its identity: the sibling
                    # retry after a gateway crash re-submits the SAME
                    # request_id (replica-side duplicate-id eviction
                    # keeps at most one live stream tier-wide)
                    request.request_id = str(body["request_id"])
                self._stream_generate(request, resume=resume)
                return
            # blocking unary call: the handler thread IS the caller's
            # connection; backpressure resolves instantly, decode blocks
            # until the dispatcher delivers
            result = gateway.submit_and_wait(request)
            code = _STATUS_HTTP.get(result.status, 500)
            payload = {
                "request_id": result.request_id,
                "status": result.status,
            }
            if result.status == "ok":
                payload.update(
                    tokens=result.tokens, replica=result.replica,
                    attempts=result.attempts, hedged=result.hedged,
                )
            else:
                payload["error"] = result.error
            self._send(code, payload)

        # -- streaming pass-through ------------------------------------
        def _chunk(self, data: bytes) -> None:
            # the replica endpoint's framing helpers, shared so the two
            # SSE surfaces cannot drift apart
            from kubegpu_tpu.gateway.dataplane import write_chunk

            write_chunk(self.wfile, data)

        def _stream_generate(self, request, resume: int = 0) -> None:
            """SSE pass-through: committed token batches relayed from
            the data plane as they stream off the replica, then the
            terminal result.  The done event's token list is
            AUTHORITATIVE.  GREEDY streams hedge: the StreamRelay dedups
            by absolute token index (greedy decode is deterministic, so
            a hedge twin's stream is the primary's), and the relay's
            emitted watermark rides down to the twin so it fast-forwards
            past tokens the caller already has — each token arrives
            exactly once whichever attempt supplies it.  SEED-PINNED
            sampled streams (temperature > 0 with a request seed) get
            the same treatment: position-keyed sample keys make every
            replica's stream byte-identical, so they hedge, dedup and
            resume exactly like greedy.  Only UNPINNED sampled streams
            keep the one-attempt pin: without a seed, replicas do not
            emit identical sampled streams.  A vanished caller fails the
            next write, which sets the request's abort event: the
            dispatcher cancels every in-flight attempt wire-level, so
            the replica frees the sequence's pages."""
            import queue as _queue

            from kubegpu_tpu.gateway.core import StreamRelay
            from kubegpu_tpu.gateway.dataplane import end_chunks, sse_event

            greedy = float(getattr(request, "temperature", 0.0)) == 0.0
            # deterministic = every replica reproduces the same stream:
            # greedy always; sampled when the request pins a seed
            deterministic = (
                greedy or getattr(request, "seed", None) is not None
            )
            # ``resume``: tokens the CALLER already holds — a client
            # resuming a crashed sibling gateway's stream passes its
            # received count as "resume_watermark", the relay skips
            # that prefix, and the dispatcher ships it down the wire so
            # the replica fast-forwards emission (deterministic streams
            # only; decode still runs from 0 and determinism keeps it
            # token-identical)
            relay = StreamRelay(gateway.metrics, dedup=deterministic,
                                base=resume if deterministic else 0)
            request.on_tokens = relay.on_tokens
            request.stream_watermark = relay.emitted
            request.abort = threading.Event()
            # unpinned sampled streams never hedge (incoherent twin
            # streams); greedy and seed-pinned streams hedge through
            # the relay's dedup
            request.no_hedge = not deterministic
            gateway.metrics.inc("gateway_stream_requests_total")
            pending = gateway.submit(request)
            # ONLY a refusal short-circuits to plain JSON (429): any
            # other instantly-resolved result (a fast completion racing
            # this check) must still stream its terminal event, tokens
            # included — the SSE contract the caller asked for
            if pending.wait(0.0) and pending.result().status == "rejected":
                result = pending.result()
                self._send(_STATUS_HTTP.get(result.status, 500), {
                    "request_id": result.request_id,
                    "status": result.status, "error": result.error,
                })
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                while not pending.wait(0.0):
                    try:
                        delta = relay.q.get(timeout=0.2)
                    except _queue.Empty:
                        self._chunk(b": ping\n\n")
                        continue
                    gateway.metrics.inc(
                        "gateway_stream_tokens_total", len(delta)
                    )
                    self._chunk(sse_event("tokens", {"tokens": delta}))
                # late deltas queued between the winner's resolution and
                # this check still belong to the caller's stream
                tail = relay.drain()
                if tail:
                    gateway.metrics.inc(
                        "gateway_stream_tokens_total", len(tail)
                    )
                    self._chunk(sse_event("tokens", {"tokens": tail}))
                result = pending.result()
                payload = {
                    "request_id": result.request_id,
                    "status": result.status,
                }
                if result.status == "ok":
                    payload.update(
                        tokens=result.tokens, replica=result.replica,
                        attempts=result.attempts, hedged=result.hedged,
                    )
                    event = "done"
                else:
                    payload["error"] = result.error
                    event = "error"
                self._chunk(sse_event(event, payload))
                end_chunks(self.wfile)
            except (BrokenPipeError, ConnectionResetError, OSError):
                # the caller vanished: propagate the cancel downstream
                # (dispatcher counts gateway_stream_disconnects_total)
                request.abort.set()
                self.close_connection = True

    return Handler


def _debug_state(gateway: Gateway, registry: ReplicaRegistry) -> dict:
    outcomes: dict = {}
    for r in gateway.results().values():
        outcomes[r.status] = outcomes.get(r.status, 0) + 1
    return {
        "replicas": [
            {
                "key": r.key, "group": r.group, "node": r.node,
                "slice": r.slice_id, "chips": sorted(map(list, r.coords)),
                "healthy": r.healthy, "reason": r.reason,
            }
            for r in registry.all()
        ],
        "queue_depth": gateway.queue.depth(),
        "in_flight": gateway.in_flight(),
        "outstanding": dict(gateway.dispatcher.outstanding),
        # the overload ladder rung the controller holds this instance at
        # (0 = none): operators see a browned-out gateway at a glance
        "brownout": gateway.brownout_level,
        "draining_replicas": sorted(registry.draining_keys()),
        "outcomes": outcomes,
        "completed_by_replica": dict(gateway.completed_by_replica),
        # each wired replica's advertised serving mesh (tensor-parallel
        # width; duck-typed off the data-plane client so third-party
        # clients without the surface degrade to {})
        "replica_mesh": (
            gateway.client.advertised()
            if hasattr(gateway.client, "advertised") else {}
        ),
    }


def _debug_trace(gateway: Gateway, path: str) -> dict:
    """The ``GET /debug/trace`` body: recent completed span trees
    (newest first) plus each reachable replica's per-iteration serving
    ledger — the one page that answers "where did that request's TTFT
    go" and "what is the pool doing" without touching the replica."""
    limit = 32
    if "?" in path:
        from urllib.parse import parse_qs

        qs = parse_qs(path.split("?", 1)[1])
        try:
            limit = max(1, int(qs.get("n", ["32"])[0]))
        except ValueError:
            pass
    tracer = gateway.tracer
    ledgers = getattr(gateway.client, "ledgers", None)
    return {
        "tracing": tracer is not None,
        "open_traces": tracer.open_count() if tracer is not None else 0,
        "evicted_traces": tracer.evicted if tracer is not None else 0,
        "traces": (
            tracer.dump_traces(limit=limit) if tracer is not None else []
        ),
        "ledgers": ledgers() if ledgers is not None else {},
    }


class _GatewayHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def handle_error(self, request, client_address):
        log.debug("connection error from %s", client_address, exc_info=True)


class GatewayServer:
    """Owns the HTTP server + registry refresh loop + node/pod watches +
    the gateway dispatcher pool (the ExtenderServer shape)."""

    def __init__(
        self,
        gateway: Gateway,
        listen: Tuple[str, int] = ("127.0.0.1", 8600),
        refresh_interval_s: float = 10.0,
        watch: bool = True,
    ) -> None:
        self.gateway = gateway
        self.registry = gateway.registry
        self.httpd = _GatewayHTTPServer(
            listen, make_handler(gateway, self.registry)
        )
        self.refresh_interval_s = refresh_interval_s
        self.watch = watch
        self._stop = threading.Event()
        self._threads = []

    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    def start(self) -> None:
        self.registry.refresh()
        self.gateway.start()
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        r = threading.Thread(target=self._refresh_loop, daemon=True)
        r.start()
        self._threads.append(r)
        if self.watch:
            # event-driven drain: a chip death propagates the same cycle
            self._threads.extend(self.registry.start_watches(self._stop))

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            try:
                self.registry.refresh()
            except Exception:  # noqa: BLE001
                log.exception("registry refresh failed; keeping stale set")

    def begin_graceful_shutdown(self, grace_s: float = 30.0,
                                done=None) -> None:
        """The SIGTERM path: flip /readyz to 503 and refuse new
        admissions NOW (``Gateway.begin_drain`` — the load balancer
        stops sending, racing submits get the retryable shutdown
        error), let in-flight requests — live streams included — finish
        within ``grace_s``, then stop the process.  ``done`` (an Event)
        is set after the final stop, so a caller blocked on it exits
        cleanly."""
        self.gateway.begin_drain()
        log.info("SIGTERM: draining (readyz=503, %.0fs grace)", grace_s)

        def _drain_then_stop():
            drained = self.gateway.drain(grace_s)
            log.info(
                "drain %s; stopping",
                "complete" if drained else f"timed out after {grace_s}s",
            )
            self.stop()
            if done is not None:
                done.set()

        t = threading.Thread(target=_drain_then_stop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        close = getattr(self.registry.api, "close_watches", None)
        if close is not None:
            close()
        self.gateway.stop()
        self.httpd.shutdown()
        self.httpd.server_close()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _build_fake_serving_cluster(preset: str, replicas: int, group: str,
                                token_budget=None, speculate_k=None,
                                decode_page_cache="off", kv_dtype=None,
                                tp=1, priority=None):
    """Fabricated cluster + scheduled decode replicas + SimBatcher-backed
    in-memory data plane: the full serving path with zero dependencies."""
    from kubegpu_tpu.gateway.client import InMemoryReplicaClient, SimBatcher
    from kubegpu_tpu.scheduler import Scheduler
    from kubegpu_tpu.scheduler.server import build_fake_cluster
    from kubegpu_tpu.testing.fake_serving import schedule_decode_replicas

    api = build_fake_cluster(preset)
    sched = Scheduler(api)
    sched.cache.refresh()
    try:
        schedule_decode_replicas(
            api, sched, replicas, group, name_prefix=group,
            priority=priority,
        )
    except AssertionError as e:
        raise SystemExit(str(e))
    registry = ReplicaRegistry(api, group=group)
    # a realistic per-step decode latency: with instant decode the
    # outstanding counts never build and least-outstanding degenerates to
    # its name tiebreak — the demo should demonstrate load spreading
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(
            slots=8, token_budget=token_budget, speculate_k=speculate_k,
            decode_page_cache=decode_page_cache, kv_dtype=kv_dtype,
            tp=tp,
        ),
        step_delay_s=0.002,
    )
    registry.subscribe(client.sync_live)
    registry.refresh()
    return api, sched, registry, client


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--listen", default="127.0.0.1:8600")
    ap.add_argument(
        "--group", default="decode",
        help="serving group to route for (kubegpu-tpu/serving-group value)",
    )
    ap.add_argument(
        "--fake-cluster", metavar="PRESET",
        help="serve a fabricated in-memory cluster + SimBatcher replicas "
        "(e.g. v5e-16) instead of connecting to a real API server",
    )
    ap.add_argument("--replicas", type=int, default=3,
                    help="replica count for --fake-cluster mode")
    ap.add_argument(
        "--replica-endpoint", action="append", default=None,
        metavar="HOST:PORT",
        help="repeatable: dispatch to these replica HTTP serving "
        "endpoints (models.worker --serve-http) over a fabricated "
        "registry instead of discovering pods from a cluster — the "
        "multi-process deployment smoke (make dryrun's "
        "dryrun_gateway_pods) and any no-k8s bring-up.  Endpoints map "
        "to fabricated replica keys in sorted order, identically in "
        "every gateway process, so N gateways over the same endpoint "
        "list route sessions identically",
    )
    ap.add_argument(
        "--session-store", default=None, metavar="URL",
        help="external session-KV store (python -m "
        "kubegpu_tpu.gateway.sessionstore; deploy/session-store.yaml), "
        "e.g. http://session-store:8650 — sealed-KV failover insurance "
        "then survives THIS gateway pod's death, which is what makes "
        "deploy/gateway.yaml replicas: 2 a real deployment.  Store "
        "outages degrade sessions to cold prefill (counted as "
        "gateway_session_store_degraded_total), never request errors.  "
        "Default: in-process store (single-pod semantics)",
    )
    ap.add_argument(
        "--router", default="least-outstanding",
        choices=("least-outstanding", "consistent-hash", "affinity",
                 "prefix-locality"),
        help="routing policy: least-outstanding (default), "
        "consistent-hash (the tier policy — N gateway pods route every "
        "session identically with zero shared state; required for "
        "multi-gateway deployments), affinity (sticky per-instance "
        "pins), or prefix-locality (route by longest locally-cached "
        "prefix, consistent-hash fallback; requires --prefix-tier)",
    )
    ap.add_argument(
        "--prefix-tier", action="store_true",
        help="fleet-wide shared-prefix KV tier (gateway/prefixtier): "
        "sealed chains publish to the store keyed by cumulative "
        "content hash (popularity-weighted LRU, payload bytes "
        "refcounted/deduped); cold dispatch targets import the longest "
        "stored prefix before prefill, so a hot system prompt "
        "prefills ONCE fleet-wide.  Uses --session-store when given "
        "(the same store serves both key classes), else an in-process "
        "backend.  Tier outages degrade to counted cold prefill "
        "(gateway_prefix_tier_degraded_total), never request errors",
    )
    ap.add_argument(
        "--prefix-page", type=int, default=8,
        help="KV page size the prefix tier hashes prompts at — must "
        "match the replicas' --page-size or probes never hit",
    )
    ap.add_argument(
        "--drain-grace", type=float, default=30.0,
        help="SIGTERM grace: /readyz flips to 503 and new admissions "
        "refuse immediately; in-flight requests (live streams "
        "included) get this long to finish before the process exits",
    )
    ap.add_argument(
        "--autoscale", action="store_true",
        help="run the serving↔scheduling FleetController in this "
        "process (kubegpu_tpu/controller): reconcile ticks watch SLO "
        "pressure (admission backlog + TTFT, EWMA-smoothed with "
        "hysteresis/cooldowns) and reshape the fleet — scale-ups "
        "gang-schedule new serving pods through grpalloc, preempting "
        "batch jobs with checkpoint-and-requeue; scale-downs drain "
        "(KV migrates) before releasing chips; overload walks the "
        "brownout ladder instead of failing.  Run it on exactly ONE "
        "gateway instance of a deployment (a sidecar leader elector "
        "gates the flag; two controllers would race reshape "
        "decisions).  Works in-cluster and with --fake-cluster; "
        "incompatible with --replica-endpoint (no cluster to reshape)",
    )
    ap.add_argument("--autoscale-min", type=int, default=1,
                    help="replica floor for --autoscale")
    ap.add_argument("--autoscale-max", type=int, default=4,
                    help="replica ceiling for --autoscale")
    ap.add_argument(
        "--autoscale-queue-target", type=float, default=8.0,
        help="backlog (queued + in-flight) per replica the pressure "
        "signal normalizes against",
    )
    ap.add_argument(
        "--autoscale-ttft-target", type=float, default=0.5,
        help="TTFT SLO target (seconds) the pressure signal "
        "normalizes against",
    )
    ap.add_argument("--autoscale-interval", type=float, default=2.0,
                    help="seconds between reconcile ticks")
    ap.add_argument(
        "--autoscale-ratio", action="store_true",
        help="let the controller also steer the prefill:decode role "
        "RATIO (disaggregated serving): sustained TTFT pressure "
        "converts a flex replica to a dedicated prefill front-end, "
        "sustained ITL pressure converts it back, and a failing "
        "handoff path collapses the fleet to co-located serving "
        "until it clears",
    )
    ap.add_argument(
        "--autoscale-itl-target", type=float, default=0.05,
        help="--autoscale-ratio: inter-token-latency SLO target "
        "(seconds) — the decode-side pressure term",
    )
    ap.add_argument(
        "--autoscale-chips-per-replica", type=int, default=1,
        help="chip request stamped on scale-up pods",
    )
    ap.add_argument(
        "--autoscale-priority", type=int, default=100,
        help="priority of scale-up serving pods — must out-rank the "
        "batch jobs they may preempt, and EXISTING serving replicas "
        "must be deployed at it too (a replica below it reads as a "
        "preemption victim)",
    )
    ap.add_argument(
        "--autoscale-requeue-file", default=None, metavar="PATH",
        help="durable write-ahead requeue ledger (a PVC path): a "
        "controller restarted between a preemption's eviction and its "
        "checkpoint-and-requeue replays the snapshot instead of "
        "losing the batch job.  Default: in-memory (single-process "
        "lifetimes)",
    )
    ap.add_argument(
        "--autoscale-shed-tenants", default="", metavar="T1,T2",
        help="lowest-priority tenants the brownout ladder's rung 3 "
        "sheds first (comma list)",
    )
    ap.add_argument(
        "--sim-data-plane", action="store_true",
        help="in-cluster mode: wire an in-process SimBatcher data "
        "plane instead of the real HTTP one (fabricated tokens — "
        "cluster smoke only)",
    )
    ap.add_argument(
        "--replica-port", type=int, default=8700,
        help="in-cluster mode: the port each replica pod's HTTP serving "
        "endpoint listens on (models.worker --serve-http).  The gateway "
        "dispatches to podIP:port, health-checks replicas over it "
        "(/readyz goes live from real replica health), and propagates "
        "trace context across the wire",
    )
    ap.add_argument(
        "--token-budget", type=int, default=None,
        help="per-step token budget for replica batchers: bounds the "
        "rows (decode tokens + prefill chunk rows) one serving "
        "iteration processes.  Paged/dense batchers pack multi-"
        "admission prefill under it; the SimBatcher data planes here "
        "model it as a per-step advance cap.  Default: unbounded",
    )
    ap.add_argument(
        "--speculate-k", type=int, default=None,
        help="draft-then-verify speculation depth for replica batchers "
        "(OFF by default).  Requires --draft-checkpoint: greedy "
        "speculative decode is lossless for any draft, but without "
        "trained draft weights it is pure overhead.  Real paged/dense "
        "batchers verify k+1-token windows per program and bill k+1 "
        "budget rows per speculative slot; the SimBatcher data planes "
        "here model exactly that accounting",
    )
    from kubegpu_tpu.gateway.client import (
        DECODE_PAGE_CACHE_POLICIES,
        KV_DTYPES,
    )

    ap.add_argument(
        "--decode-page-cache", default="off",
        choices=list(DECODE_PAGE_CACHE_POLICIES),
        help="replica batchers' session-KV-reuse policy: seal retired "
        "sequences' DECODE-produced pages into the shared prefix cache "
        "so a session's turn 2 (same session id — the affinity router "
        "pins it to the replica holding the pages) skips re-prefilling "
        "turn 1's output.  off = prompt pages only (default); fp32 = "
        "share only at float32 serving precision (property-tested "
        "greedy-token-identical); all = any dtype (bf16 may flip "
        "near-tie argmaxes — measured in bench.py serving_multiturn).  "
        "Consumed replica-side by the real paged batchers; the "
        "in-process SimBatcher planes here only validate the contract",
    )
    ap.add_argument(
        "--kv-dtype", default=None, choices=list(KV_DTYPES),
        help="replica batchers' KV page-pool STORAGE format: int8 = "
        "per-page per-head-scaled int8 pages (half the resting pool "
        "bytes, ~2x the pool rows per byte budget, half the migration "
        "wire bytes per page; agreement/margins measured by bench.py "
        "serving_quantized_pool), bf16/fp32 = explicit full width "
        "(must match the serving dtype).  Consumed replica-side by the "
        "real paged batchers (models.worker --kv-dtype); the in-process "
        "SimBatcher planes here validate the contract, advertise the "
        "format, and refuse dtype-mismatched migrations like the real "
        "geometry check.  Default: full width at the serving dtype",
    )
    ap.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel width of each serving replica's mesh: a "
        "TP replica shards its KV page pool / prefill station / draft "
        "ring on heads over a 'model' mesh (models/worker.py --tp on "
        "the paged path), serving tp x the pool rows per replica for "
        "the same per-device HBM.  Consumed replica-side; the gateway "
        "validates the contract, and wired replicas advertise their "
        "width at /state (replica_mesh).  Default 1 (no TP)",
    )
    ap.add_argument(
        "--draft-checkpoint", default=None, metavar="DIR",
        help="orbax checkpoint directory holding the draft model's "
        "weights; required when --speculate-k is set and must exist.  "
        "Consumed REPLICA-side (models.serving.load_draft_checkpoint / "
        "worker --draft-ckpt-dir) once a real data plane is wired; the "
        "in-process SimBatcher planes here model only the multi-token "
        "step and its k+1-row budget accounting",
    )
    ap.add_argument(
        "--replica-tls-ca", default=None, metavar="PEM",
        help="CA bundle to verify replica serving endpoints against: "
        "the data plane dispatches over HTTPS instead of plain HTTP "
        "(replicas run models.worker --serve-http-tls-cert/key).  "
        "Omit for plain HTTP (loopback / single-tenant)",
    )
    ap.add_argument(
        "--replica-auth-token-file", default=None, metavar="FILE",
        help="bearer token (file contents) sent on every replica "
        "data-plane request; replicas started with "
        "--serve-http-auth-token-file gate /v1/* on it",
    )
    ap.add_argument("--queue-capacity", type=int, default=256)
    ap.add_argument("--per-tenant-cap", type=int, default=None)
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-request end-to-end deadline (seconds)")
    ap.add_argument("--hedge-after", type=float, default=1.0,
                    help="straggler threshold before a hedged dispatch")
    ap.add_argument("--dispatchers", type=int, default=8)
    ap.add_argument("--refresh-interval", type=float, default=10.0)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.token_budget is not None and args.token_budget <= 0:
        ap.error(f"--token-budget must be positive, got {args.token_budget}")
    if args.tp < 1:
        ap.error(f"--tp must be >= 1, got {args.tp}")
    if args.speculate_k is not None:
        # the --token-budget pattern: malformed serving knobs die at
        # argparse time, never mid-serve-loop
        if args.speculate_k < 1:
            ap.error(
                f"--speculate-k must be >= 1, got {args.speculate_k}"
            )
        if args.draft_checkpoint is None:
            ap.error(
                "--speculate-k requires --draft-checkpoint: greedy "
                "speculation is lossless for any draft, but an untrained "
                "draft is pure overhead — point at trained draft weights"
            )
        if not os.path.isdir(args.draft_checkpoint):
            ap.error(
                f"--draft-checkpoint {args.draft_checkpoint!r} is not a "
                "directory: the draft restore would fail replica-side — "
                "a typo'd path must die here, not after deployment"
            )
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)

    # the serving↔scheduling loop's inputs: an API server handle + a
    # Scheduler over it (both stateless over annotations, so a
    # controller-embedded instance re-derives the same world the
    # extender sees).  Populated by the modes that have a cluster.
    ctrl_api = ctrl_sched = None
    if args.replica_endpoint:
        # explicit-endpoint mode: a fabricated registry (the shared
        # fake-cluster bring-up, so replica keys are DETERMINISTIC —
        # every gateway process over the same endpoint list agrees) in
        # front of REAL replica HTTP serving endpoints.  This is the
        # multi-process deployment smoke: N gateway processes + one
        # worker + one session store, no k8s.
        from kubegpu_tpu.gateway.dataplane import HttpReplicaClient
        from kubegpu_tpu.testing.fake_serving import (
            build_fake_serving_stack,
        )

        # SORTED endpoints: replica keys are assigned in sorted order,
        # so the key→endpoint binding must not depend on CLI argument
        # order — two gateways given the same endpoints in different
        # order would otherwise route one session to different workers
        endpoints = sorted(args.replica_endpoint)
        stack = build_fake_serving_stack(
            len(endpoints), group=args.group
        )
        registry = stack.registry
        client = HttpReplicaClient()
        registry.refresh()
        keys = sorted(r.key for r in registry.all())
        for key, addr in zip(keys, endpoints):
            client.set_endpoint(key, addr)
        # real data-plane health: the fabricated annotations say every
        # replica is fine forever; the probe is what drains a replica
        # whose serving process died (and re-admits it after a cold
        # restart on the same endpoint)
        registry.probe = client.probe
        registry.subscribe(client.sync_live)
        registry.refresh()
        log.info(
            "explicit-endpoint data plane: %s",
            dict(zip(keys, endpoints)),
        )
    elif args.fake_cluster:
        ctrl_api, ctrl_sched, registry, client = (
            _build_fake_serving_cluster(
                args.fake_cluster, args.replicas, args.group,
                token_budget=args.token_budget,
                speculate_k=args.speculate_k,
                decode_page_cache=args.decode_page_cache,
                kv_dtype=args.kv_dtype, tp=args.tp,
                # the preemption contract: serving replicas must be
                # deployed AT the controller's serving priority, or an
                # unstamped replica (default 0) reads as a victim and a
                # surge's scale-up cannibalizes the live serving fleet
                priority=(
                    args.autoscale_priority if args.autoscale else None
                ),
            )
        )
    else:
        from kubegpu_tpu.utils.apiserver import KubeApiServer

        ctrl_api = KubeApiServer()
        registry = ReplicaRegistry(ctrl_api, group=args.group)
        if args.autoscale:
            from kubegpu_tpu.scheduler import Scheduler

            ctrl_sched = Scheduler(ctrl_api)
            ctrl_sched.resync()
        from kubegpu_tpu.gateway.client import InMemoryReplicaClient

        if not args.sim_data_plane:
            # the REAL in-cluster data plane: stream from each replica
            # pod's HTTP serving endpoint (models.worker --serve-http) at
            # its discovered podIP.  The registry additionally
            # health-checks every replica's /healthz each refresh, so
            # /readyz is live serving capacity, not just annotation
            # bookkeeping — the 503 fail-safe this flag-day retires.
            from kubegpu_tpu.gateway.dataplane import HttpReplicaClient

            def _resolve(key, _port=args.replica_port):
                # one snapshot read: the refresh thread swaps the
                # registry table concurrently
                info = registry.get(key)
                if info is None or not info.addr:
                    return None
                return f"{info.addr}:{_port}"

            replica_token = None
            if args.replica_auth_token_file:
                with open(args.replica_auth_token_file) as f:
                    replica_token = f.read().strip()
            client = HttpReplicaClient(
                resolver=_resolve,
                default_port=args.replica_port,
                tls_ca=args.replica_tls_ca,
                auth_token=replica_token,
            )
            registry.probe = client.probe
            registry.subscribe(client.sync_live)
            log.info(
                "HTTP data plane: dispatching to replica pods on port "
                "%d (podIP discovery + /healthz probes)",
                args.replica_port,
            )
        else:
            # OPT-IN in-process data plane (cluster smoke tests): every
            # replica the registry discovers gets a worker driving a
            # local SimBatcher, so the gateway is live end to end and
            # /readyz goes 200 the moment a replica is wired — 503
            # again only when the registry drains to zero.  Tokens are
            # fabricated; never expose this to real clients.
            from kubegpu_tpu.gateway.client import SimBatcher

            client = InMemoryReplicaClient(
                batcher_factory=lambda key: SimBatcher(
                    slots=8, token_budget=args.token_budget,
                    speculate_k=args.speculate_k,
                    decode_page_cache=args.decode_page_cache,
                    kv_dtype=args.kv_dtype,
                    tp=args.tp,
                ),
                step_delay_s=0.002,
            )
            registry.subscribe(client.sync_live)
            log.warning(
                "--sim-data-plane: serving FABRICATED tokens from "
                "in-process SimBatchers — cluster smoke only"
            )
    from kubegpu_tpu.gateway.failover import FailoverPolicy, SessionKVStore
    from kubegpu_tpu.utils.metrics import default_metrics

    session_store = None
    if args.session_store:
        # the external insurance store: sealed-KV captures survive this
        # pod's death.  Short per-op deadlines + breaker: with the
        # store down, every session degrades to cold prefill at full
        # speed (one fast-fail per op, never a connect timeout per
        # request)
        from kubegpu_tpu.gateway.sessionstore import HttpStoreClient

        session_store = SessionKVStore(
            backend=HttpStoreClient(
                args.session_store, metrics=default_metrics
            ),
            metrics=default_metrics,
        )
        log.info("external session store: %s", args.session_store)

    prefix_tier = None
    if args.prefix_tier:
        # the SAME external store serves both key classes (session
        # leases + prefix chains) when --session-store is given; else
        # an in-process backend (single-pod semantics)
        from kubegpu_tpu.gateway.prefixtier import PrefixTier

        if args.session_store:
            from kubegpu_tpu.gateway.sessionstore import HttpStoreClient

            tier_backend = HttpStoreClient(
                args.session_store, metrics=default_metrics
            )
        else:
            from kubegpu_tpu.gateway.sessionstore import (
                InProcessStoreBackend,
            )

            tier_backend = InProcessStoreBackend()
        prefix_tier = PrefixTier(
            backend=tier_backend, page=args.prefix_page,
            metrics=default_metrics,
        )
        log.info(
            "prefix tier: page=%d store=%s", args.prefix_page,
            args.session_store or "in-process",
        )

    router = None
    if args.router == "consistent-hash":
        from kubegpu_tpu.gateway.router import ConsistentHashRouter

        router = ConsistentHashRouter()
    elif args.router == "affinity":
        from kubegpu_tpu.gateway.router import SessionAffinityRouter

        router = SessionAffinityRouter()
    elif args.router == "prefix-locality":
        if prefix_tier is None:
            raise SystemExit("--router prefix-locality needs --prefix-tier")
        from kubegpu_tpu.gateway.router import PrefixLocalityRouter

        router = PrefixLocalityRouter(prefix_tier)

    gateway = Gateway(
        registry, client,
        router=router,
        queue=AdmissionQueue(args.queue_capacity, args.per_tenant_cap),
        policy=FailoverPolicy(
            deadline_s=args.deadline, hedge_after_s=args.hedge_after
        ),
        dispatchers=args.dispatchers,
        session_store=session_store,
        prefix_tier=prefix_tier,
    )
    host, _, port = args.listen.rpartition(":")
    server = GatewayServer(
        gateway,
        listen=(host or "127.0.0.1", int(port)),
        refresh_interval_s=args.refresh_interval,
    )
    server.start()
    log.info("gateway listening on http://%s:%d", *server.address)
    # a parseable announce line (the worker's REPLICA_HTTP_SERVING
    # shape): subprocess harnesses bind port 0 and read the real one
    print(f"GATEWAY_HTTP_SERVING port={server.address[1]}", flush=True)
    import signal

    shutdown = threading.Event()
    if args.autoscale:
        if ctrl_api is None or ctrl_sched is None:
            raise SystemExit(
                "--autoscale needs a cluster to reshape: run it "
                "in-cluster or with --fake-cluster, not "
                "--replica-endpoint"
            )
        from kubegpu_tpu.controller import (
            ControllerConfig,
            FleetController,
            JsonFileRequeueBackend,
            RequeueLedger,
        )

        ledger = None
        if args.autoscale_requeue_file:
            ledger = RequeueLedger(
                JsonFileRequeueBackend(args.autoscale_requeue_file)
            )
        controller = FleetController(
            api=ctrl_api, sched=ctrl_sched, registry=registry,
            gateway=gateway, client=client,
            requeue_ledger=ledger,
            config=ControllerConfig(
                group=args.group,
                chips_per_replica=args.autoscale_chips_per_replica,
                serving_priority=args.autoscale_priority,
                min_replicas=args.autoscale_min,
                max_replicas=args.autoscale_max,
                queue_target_per_replica=args.autoscale_queue_target,
                ttft_target_s=args.autoscale_ttft_target,
                ratio_enabled=args.autoscale_ratio,
                itl_target_s=args.autoscale_itl_target,
                shed_tenants=tuple(
                    t for t in args.autoscale_shed_tenants.split(",")
                    if t
                ),
            ),
        )
        threading.Thread(
            target=controller.run_forever,
            args=(args.autoscale_interval, shutdown),
            daemon=True, name="fleet-controller",
        ).start()
        log.info(
            "fleet controller: reconciling every %.1fs "
            "(replicas %d..%d, queue target %.1f/replica, "
            "TTFT target %.2fs)",
            args.autoscale_interval, args.autoscale_min,
            args.autoscale_max, args.autoscale_queue_target,
            args.autoscale_ttft_target,
        )
    # SIGTERM = GRACEFUL: readyz 503 + refuse new admissions, finish
    # in-flight streams within --drain-grace, then exit 0 — the
    # per-instance lifecycle a load balancer can act on
    signal.signal(
        signal.SIGTERM,
        lambda *_: server.begin_graceful_shutdown(
            args.drain_grace, done=shutdown
        ),
    )
    try:
        # wait in a timeout LOOP: a bare Event.wait() parks the main
        # thread in an uninterruptible lock acquire and the SIGTERM
        # handler never runs — the graceful drain needs the main thread
        # to keep servicing signals
        while not shutdown.wait(0.2):
            pass
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
