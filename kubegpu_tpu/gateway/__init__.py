"""L5 serving gateway: the cluster-level inference front door.

Bridges the two halves of the serving stack: the control plane places
decode replicas on ICI-contiguous chips (scheduler/grpalloc/crishim) and
the data plane decodes inside one replica (models/serving.py); the
gateway routes cluster traffic TO those replicas — discovery from the
same annotations the scheduler writes, bounded fair admission, load-aware
routing, and deadline/retry/hedge failover.  Same architecture split as
the scheduler: pure core + thin HTTP codec, every cluster dependency
behind ``ApiServer``, every data-plane dependency behind
``ReplicaClient`` — the whole subsystem runs in-memory under test.
"""

from kubegpu_tpu.gateway.client import (
    Attempt,
    AttemptResult,
    InMemoryReplicaClient,
    ReplicaClient,
    SimBatcher,
    sim_stream_seed,
)
from kubegpu_tpu.gateway.core import (
    Gateway,
    GatewayRequest,
    GatewayResult,
    PendingRequest,
    StreamRelay,
)
from kubegpu_tpu.gateway.hashring import ConsistentHashRing
from kubegpu_tpu.gateway.dataplane import (
    HttpReplicaClient,
    ReplicaServer,
    ReplicaServingLoop,
)
from kubegpu_tpu.gateway.failover import (
    Dispatcher,
    FailoverPolicy,
    SessionKVStore,
)
from kubegpu_tpu.gateway.prefixtier import PrefixTier, prompt_chain_keys
from kubegpu_tpu.gateway.queue import AdmissionQueue, QueueClosed, QueueFull
from kubegpu_tpu.gateway.registry import ReplicaInfo, ReplicaRegistry
from kubegpu_tpu.gateway.router import (
    ConsistentHashRouter,
    LeastOutstandingRouter,
    PrefixLocalityRouter,
    Router,
    SessionAffinityRouter,
)
from kubegpu_tpu.gateway.server import GatewayServer
from kubegpu_tpu.gateway.sessionstore import (
    CircuitBreaker,
    HttpStoreClient,
    InProcessStoreBackend,
    SessionStoreBackend,
    StoreResult,
    StoreServer,
)
from kubegpu_tpu.gateway.tier import GatewayTier, is_gateway_death

__all__ = [
    "AdmissionQueue",
    "Attempt",
    "AttemptResult",
    "CircuitBreaker",
    "ConsistentHashRing",
    "ConsistentHashRouter",
    "Dispatcher",
    "HttpStoreClient",
    "InProcessStoreBackend",
    "SessionStoreBackend",
    "StoreResult",
    "StoreServer",
    "FailoverPolicy",
    "Gateway",
    "GatewayRequest",
    "GatewayResult",
    "GatewayServer",
    "GatewayTier",
    "HttpReplicaClient",
    "InMemoryReplicaClient",
    "LeastOutstandingRouter",
    "ReplicaServer",
    "ReplicaServingLoop",
    "PendingRequest",
    "PrefixLocalityRouter",
    "PrefixTier",
    "prompt_chain_keys",
    "QueueClosed",
    "QueueFull",
    "ReplicaClient",
    "ReplicaInfo",
    "ReplicaRegistry",
    "Router",
    "SessionAffinityRouter",
    "SessionKVStore",
    "SimBatcher",
    "StreamRelay",
    "is_gateway_death",
    "sim_stream_seed",
]
