"""Failover: deadlines, retry-with-budget, hedged dispatch.

One request's dispatch lifecycle, run on a gateway dispatcher thread:

    pick primary → submit → (straggling? submit ONE hedge elsewhere)
                 → first completion wins, losers cancelled
                 → error? retry on a different replica, if budget allows
                 → deadline passed? cancel everything, fail explicitly

Both hedges and retries are BUDGETED (the classic retry-budget shape:
issued extra attempts may not exceed ``ratio`` of requests seen, with a
small burst floor) — without the budget, a brown-out replica turns every
request into 2-3 requests and the gateway amplifies its own overload
into a full outage.  The winner's result is delivered exactly once; a
hedge loser's completion is cancelled and discarded, never surfaced —
the soak's I5 invariant (served exactly once) leans on this.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from kubegpu_tpu.gateway.client import Attempt, ReplicaClient
from kubegpu_tpu.gateway.registry import ReplicaInfo
from kubegpu_tpu.gateway.router import Router, handoff_rank_key
# SessionKVStore moved to gateway/sessionstore.py when it grew pluggable
# backends (external HTTP store, PR 13); re-exported here because this
# module is its historical home and half the stack imports it from here.
from kubegpu_tpu.gateway.sessionstore import SessionKVStore  # noqa: F401
from kubegpu_tpu.utils.metrics import Metrics

log = logging.getLogger(__name__)

_POLL_S = 0.002  # attempt-completion poll; decode steps are >> this


class _TraceView:
    """Request view carrying per-attempt trace context (and, for
    routing, the open route span) without mutating the caller's request
    — the router's ``_with_hint`` pattern: attempts race, so the shared
    request object must never hold attempt-scoped state."""

    def __init__(self, request, **attrs) -> None:
        self._request = request
        for k, v in attrs.items():
            setattr(self, k, v)

    def __getattr__(self, name):
        return getattr(self._request, name)


@dataclass
class FailoverPolicy:
    deadline_s: float = 30.0        # end-to-end cap per request
    hedge_after_s: float = 1.0      # straggler threshold before hedging
    max_attempts: int = 3           # primary + retries (hedges NOT counted)
    retry_budget_ratio: float = 0.2  # retries ≤ ratio · requests + floor
    hedge_budget_ratio: float = 0.1  # hedges  ≤ ratio · requests + floor
    budget_floor: int = 10           # burst allowance while requests ≈ 0


class _Budget:
    """issued ≤ ratio · seen + floor — cheap, lock-protected, monotonic."""

    def __init__(self, ratio: float, floor: int) -> None:
        self.ratio = ratio
        self.floor = floor
        self.seen = 0
        self.issued = 0
        self._lock = threading.Lock()

    def observe(self) -> None:
        with self._lock:
            self.seen += 1

    def try_spend(self) -> bool:
        with self._lock:
            if self.issued < self.ratio * self.seen + self.floor:
                self.issued += 1
                return True
            return False


class Dispatcher:
    """Drives one request through attempts against the ReplicaClient.

    Shared across gateway dispatcher threads: the budgets and outstanding
    counts are global on purpose (a per-thread budget would defeat the
    amplification bound).
    """

    def __init__(
        self,
        client: ReplicaClient,
        router: Router,
        policy: Optional[FailoverPolicy] = None,
        metrics: Optional[Metrics] = None,
        outstanding=None,
        outstanding_lock: Optional[threading.Lock] = None,
        session_store: Optional[SessionKVStore] = None,
        prefix_tier=None,
    ) -> None:
        self.client = client
        self.router = router
        self.policy = policy or FailoverPolicy()
        self.metrics = metrics
        # sealed-export restore at dispatch: when a routed session lost
        # its home replica, its captured KV is imported into the target
        # BEFORE the attempt opens — the re-pin becomes a transfer.
        # Mispin restores (away-dispatch with the home still healthy)
        # only make sense when the router MEANS its away-dispatches —
        # affinity/ring routers (duck-typed on forget_replica, which
        # both define for the drain path).  A plain load balancer
        # bounces sessions by design; restoring per bounce would ship
        # the payload every turn.
        self.session_store = session_store
        # fleet-wide prefix tier (prefixtier.PrefixTier): before a cold
        # attempt opens, import the longest fleet-published prefix of the
        # prompt into the target, so a hot system prompt prefills once
        # fleet-wide instead of once per replica.
        self.prefix_tier = prefix_tier
        self._mispin_restore = hasattr(router, "forget_replica")
        self.retry_budget = _Budget(
            self.policy.retry_budget_ratio, self.policy.budget_floor
        )
        self.hedge_budget = _Budget(
            self.policy.hedge_budget_ratio, self.policy.budget_floor
        )
        # replica key -> in-flight count, shared with the router's callers
        self.outstanding = outstanding if outstanding is not None else {}
        self._out_lock = outstanding_lock or threading.Lock()
        # brownout rung 1 (Gateway.set_brownout): a browned-out fleet
        # must not amplify its own overload with duplicate dispatches
        self.hedge_disabled = False
        # prefill/decode disaggregation: when a prefill-only replica
        # announces a sequence SEALED (parked, zero tokens emitted), the
        # loop below hands it off to a decode replica over the migration
        # verbs.  The controller's brownout ladder flips this off
        # (collapse to co-located) when handoff capacity is the
        # bottleneck; sealed events still arriving from a replica whose
        # role hasn't flipped yet MUST still be handled — a parked
        # sequence decodes nowhere until a handoff (or the local
        # fallback) lands, so the collapse only changes the TARGET
        # ranking, never whether we act.
        self.disaggregation = True
        # streamed seal-time handoff: the dispatcher loop ships pages
        # sealed so far to the adjacency-picked decode target DURING the
        # remaining prefill compute, so only the final delta + cursor
        # handoff rides the TTFT critical path.  ``stream_handoff=False``
        # forces the one-shot transfer (the bench's comparison lane);
        # ``delta_poll_s`` bounds seal-watch poll pressure on the
        # source's serving thread.
        self.stream_handoff = True
        self.delta_poll_s = 0.01

    # -- outstanding bookkeeping ------------------------------------------
    def _inc(self, key: str) -> None:
        with self._out_lock:
            self.outstanding[key] = self.outstanding.get(key, 0) + 1

    def _dec(self, key: str) -> None:
        with self._out_lock:
            n = self.outstanding.get(key, 1) - 1
            if n <= 0:
                self.outstanding.pop(key, None)
            else:
                self.outstanding[key] = n

    def _submit(self, replica: ReplicaInfo, request, attempt_n: int = 1,
                hedge: bool = False) -> Attempt:
        self._inc(replica.key)
        trace = getattr(request, "trace", None)
        restored = False
        if self.session_store is not None and not hedge:
            # restore-before-dispatch: a session dispatching away from
            # its recorded KV home (lost home, or a ring-rebalance
            # mispin) gets its sealed export imported into THIS target
            # first, so the very request that moves already hits warm
            # pages.  NOT for hedge twins: the primary (usually on the
            # healthy home) would make the multi-megabyte transfer pure
            # waste — and if the twin wins, the session's NEXT dispatch
            # routes to it and restores then.
            try:
                if self.session_store.restore_for(
                    request, replica.key, self.client,
                    mispin_restore=self._mispin_restore,
                ):
                    restored = True
                    if self.metrics:
                        self.metrics.inc("gateway_session_restores_total")
                    if trace is not None:
                        trace.event("session_restore", replica=replica.key)
            except Exception:  # noqa: BLE001 - restore is best-effort
                log.exception("sealed-session restore failed")
        if self.prefix_tier is not None and not hedge and not restored:
            # tier probe + pre-prefill import: skipped for hedge twins
            # (the primary usually lands on warm pages already) and when
            # a session restore just shipped the same chain.  Best-effort
            # under the full degradation contract — a tier outage means a
            # counted cold prefill, never a request error.
            try:
                if self.prefix_tier.ensure_warm(
                    request, replica.key, self.client
                ) and trace is not None:
                    trace.event("prefix_tier_import", replica=replica.key)
            except Exception:  # noqa: BLE001 - tier import is best-effort
                log.exception("prefix-tier import failed")
        attrs: dict = {}
        # streaming resume watermark: an attempt opened after the caller
        # already received N tokens — a hedge twin, a retry, or a
        # sibling gateway resuming a crashed gateway's stream — carries
        # N down the wire, so the replica fast-forwards EMISSION past
        # the already-delivered prefix (it still decodes it — greedy
        # decode is deterministic, which is also why the relay's dedup
        # is sound).  A fresh request's watermark is 0 and ships nothing.
        wm_fn = getattr(request, "stream_watermark", None)
        if wm_fn is not None:
            try:
                wm = int(wm_fn())
            except Exception:  # noqa: BLE001 - watermark is advisory
                wm = 0
            if wm > 0:
                attrs["resume_watermark"] = wm
        span = None
        if trace is not None:
            # one dispatch span per attempt; the replica's serve subtree
            # nests under it (the worker passes the view's .trace into
            # batcher.submit).  overhang_ok: a hedge loser's teardown
            # legitimately lands after the gateway already recorded the
            # winner and closed the root.
            span = trace.child(
                "dispatch", replica=replica.key, attempt=attempt_n,
                hedge=hedge, overhang_ok=True,
            )
            attrs["trace"] = span
        if attrs:
            request = _TraceView(request, **attrs)
        attempt = self.client.submit(replica.key, request)
        if span is not None:
            attempt._dispatch_span = span
        attempt._routed_key = replica.key
        return attempt

    # -- post-prefill handoff (disaggregation) -----------------------------
    def _pick_handoff_target(self, src: str,
                             replicas: List[ReplicaInfo]) -> Optional[str]:
        """Best decode-side peer for a handoff from ``src``, by the
        shared adjacency score (same slice → mesh distance → load).
        None = no peer at all (the caller co-locates on the source)."""
        if not self.disaggregation:
            return None
        anchor = next((r for r in replicas if r.key == src), None)
        cand = [
            r for r in replicas
            if r.key != src and getattr(r, "role", "flex") != "prefill"
        ] or [r for r in replicas if r.key != src]
        if not cand:
            return None
        return min(
            cand,
            key=lambda r: handoff_rank_key(r, anchor, self.outstanding),
        ).key

    def _stream_deltas(self, attempt: Attempt, request, replicas_fn,
                       force: bool = False) -> None:
        """Seal-watch step: ship pages sealed since the last poll to the
        pre-picked handoff target, overlapping the wire with remaining
        prefill compute.  Strictly best-effort — a refused or lost delta
        abandons streaming and the seal-time one-shot handoff takes
        over (nothing was reclaimed yet: reclaim only runs at seal,
        against the acked watermark).  ``force`` skips the poll rate
        limit (the seal-time drain of whatever never got streamed)."""
        if not self.stream_handoff or not self.disaggregation:
            return
        client = self.client
        if getattr(client, "export_delta", None) is None:
            return
        st = getattr(attempt, "_handoff_stream", None)
        now = time.monotonic()
        if st is None:
            replicas = (
                replicas_fn() if callable(replicas_fn) else replicas_fn
            )
            src = next(
                (r for r in replicas if r.key == attempt.replica), None
            )
            target = (
                self._pick_handoff_target(attempt.replica, replicas)
                if src is not None
                and getattr(src, "role", "flex") == "prefill"
                else None
            )
            st = attempt._handoff_stream = {
                # failed=True doubles as "don't stream": non-prefill
                # source (nothing parks → nothing seals early) or no
                # decode peer to stream toward
                "target": target, "cursor": 0, "acked": 0, "deltas": 0,
                "overlap_s": 0.0, "failed": target is None,
                "next_poll": 0.0,
            }
        if st["failed"] or (not force and now < st["next_poll"]):
            return
        st["next_poll"] = now + self.delta_poll_s
        sealed_already = attempt.sealed.is_set()
        t0 = time.monotonic()
        try:
            payload = client.export_delta(attempt, request, st["cursor"])
        except Exception:  # noqa: BLE001 - streaming is best-effort
            payload = None
        if payload is None:
            return
        n = len(payload.get("page_keys") or [])
        if n == 0:
            return
        try:
            staged = client.import_delta(st["target"], payload)
        except Exception:  # noqa: BLE001 - refusal = fall back
            staged = None
        if staged is None:
            # target refused or died: its staged prefix is unreliable,
            # so forget the acked watermark and let the one-shot
            # handoff ship everything (no reclaim has happened yet)
            st["failed"] = True
            st["acked"] = 0
            return
        st["cursor"] += n
        st["acked"] = st["cursor"]
        st["deltas"] += 1
        if not sealed_already:
            st["overlap_s"] += time.monotonic() - t0
        if self.metrics:
            self.metrics.inc("gateway_phase_handoff_deltas_total")

    def _do_handoff(self, attempt: Attempt, request,
                    replicas: List[ReplicaInfo]) -> None:
        """The sequence's prompt pages sealed on a prefill-only replica
        and it PARKED (zero tokens emitted): hand it off to a decode
        replica through the migration verbs — adjacency-scored (slice
        locality first: ICI beats DCN on handoff wire time, then mesh
        distance, then load).  If the seal-watch streamed deltas, drain
        the tail, RECLAIM the acked pages on the source (they admit a
        queued prefill during this very roundtrip), and ship only the
        cursor remainder.  With no decode peer (or disaggregation
        collapsed), the source itself is the target: detach-and-resume
        locally through the same verb pair, so a parked sequence NEVER
        decodes nowhere."""
        attempt._handed_off = True
        migrate = getattr(self.client, "migrate", None)
        if migrate is None:
            return
        src = attempt.replica
        st = getattr(attempt, "_handoff_stream", None)
        cursor = 0
        target_key = None
        if st is not None and not st["failed"]:
            # final drain: everything sealed since the last poll goes
            # now, so the critical-path hop carries only the cursor
            self._stream_deltas(attempt, request, replicas, force=True)
            if not st["failed"] and st["acked"] > 0:
                cursor = st["acked"]
                target_key = st["target"]
                try:
                    self.client.reclaim(attempt, request, cursor)
                except Exception:  # noqa: BLE001 - reclaim best-effort
                    log.exception("early reclaim failed")
        if target_key is None:
            target_key = self._pick_handoff_target(src, replicas) or src
        attempt._handoff_mode = "streamed" if cursor else "oneshot"
        trace = getattr(request, "trace", None)
        if trace is not None:
            trace.event(
                "phase_handoff", source=src, target=target_key,
                mode=attempt._handoff_mode, cursor=cursor,
            )
        t0 = time.monotonic()
        ok = False
        try:
            ok = migrate(
                attempt, request, target_key, fallback=True,
                cursor=cursor,
            )
        except Exception:  # noqa: BLE001 - handoff is best-effort
            log.exception("phase handoff failed")
        if not ok and target_key != src and not attempt.done:
            # the decode-side leg never started (export lost, target
            # unresolvable): unpark locally instead.  The cursor still
            # applies — reclaimed pages re-resolve from the source's
            # own prefix cache on the fallback import.
            try:
                ok = migrate(
                    attempt, request, src, fallback=True, cursor=cursor,
                )
            except Exception:  # noqa: BLE001 - same contract
                log.exception("local handoff fallback failed")
        if self.metrics:
            self.metrics.observe(
                "gateway_phase_handoff_seconds", time.monotonic() - t0
            )
            if cursor and st is not None:
                self.metrics.observe(
                    "gateway_phase_handoff_overlap_seconds",
                    st["overlap_s"],
                )
        if not ok:
            attempt.handoff_outcome = "failed"

    def _record_handoff(self, attempt: Attempt) -> None:
        """Once per handed-off attempt, at settlement (the HTTP client
        resolves the fallback-vs-ok outcome on its reader thread, so
        the counts are only authoritative when the attempt is done)."""
        if not getattr(attempt, "_handed_off", False):
            return
        if getattr(attempt, "_handoff_recorded", False):
            return
        attempt._handoff_recorded = True
        if self.metrics:
            outcome = getattr(attempt, "handoff_outcome", "") or "failed"
            self.metrics.inc(
                "gateway_phase_handoff_total", outcome=outcome
            )
            wire = int(getattr(attempt, "handoff_wire_bytes", 0) or 0)
            if wire:
                self.metrics.inc(
                    "gateway_phase_handoff_wire_bytes_total", wire,
                    mode=getattr(attempt, "_handoff_mode", "oneshot"),
                )

    def _settle(self, attempt: Attempt) -> None:
        # the key the _inc above charged — attempt.replica may have been
        # re-homed by a live migration mid-flight
        self._dec(getattr(attempt, "_routed_key", attempt.replica))
        self._record_handoff(attempt)
        span = getattr(attempt, "_dispatch_span", None)
        if span is not None:
            res = attempt.result()
            span.end(
                outcome=(
                    "cancelled" if attempt.cancelled
                    else "ok" if (res is not None and res.ok)
                    else "error"
                ),
            )

    # -- the dispatch loop -------------------------------------------------
    def dispatch(
        self,
        request,
        live: Callable[[], List[ReplicaInfo]],
    ) -> "DispatchOutcome":
        """Run one request to a terminal outcome.  ``live`` re-reads the
        registry so retries see post-failure membership.

        The deadline is anchored at ENQUEUE (request.enqueued_at), not at
        dequeue: "end-to-end budget" includes queue wait, and the caller's
        submit_and_wait times out relative to submission — a request that
        aged out in the queue must fail fast here, not burn a replica
        decoding an answer its client already abandoned."""
        policy = self.policy
        self.retry_budget.observe()
        self.hedge_budget.observe()
        start = getattr(request, "enqueued_at", 0.0) or time.monotonic()
        deadline = start + (
            getattr(request, "deadline_s", None) or policy.deadline_s
        )
        if time.monotonic() >= deadline:
            # shed-before-work: the deadline already expired while this
            # request sat in the admission queue — dispatching it would
            # burn prefill compute on an answer its caller has abandoned.
            # Resolve as a COUNTED, RETRYABLE backpressure result (the
            # 429 shape, not a timeout: nothing was attempted).
            if self.metrics:
                self.metrics.inc(
                    "gateway_shed_total", reason="deadline_expired"
                )
            return DispatchOutcome(
                "rejected",
                error="deadline expired in queue; retry with backoff",
            )
        tried = set()
        attempts: List[Attempt] = []
        n_attempts = 0
        hedged = False
        hedge_at: Optional[float] = None
        last_error = "no live replicas"

        trace = getattr(request, "trace", None)
        route_spans_left = [16]  # a zero-replica outage polls pick in a
        # loop; the tree records the first N routing decisions, not one
        # span per poll tick

        def routed_pick(exclude: frozenset,
                        hedge: bool = False) -> Optional[ReplicaInfo]:
            # every routing decision is a span: the router annotates it
            # (session pin state, re-pins) via the request view's
            # route_span, so "why did this land there" is in the tree
            replicas = live()
            span = None
            req = request
            if trace is not None and route_spans_left[0] > 0:
                route_spans_left[0] -= 1
                # overhang_ok: a gateway kill (or caller abort) closes
                # the ROOT the instant it records the terminal result —
                # this microsecond span may legitimately still be open
                # on the dispatcher thread at that moment, like the
                # dispatch spans below
                span = trace.child("route", hedge=hedge, overhang_ok=True)
                req = _TraceView(request, route_span=span)
            target = self.router.pick(
                req, replicas, self.outstanding, exclude
            )
            if span is not None:
                span.end(
                    replica=target.key if target is not None else "",
                    live=len(replicas),
                )
                if target is not None:
                    route_spans_left[0] = max(route_spans_left[0], 4)
            return target

        def pick_target() -> Optional[ReplicaInfo]:
            # prefer a replica this request hasn't touched; fall back to
            # re-trying one (it may have recovered) rather than failing
            target = routed_pick(frozenset(tried))
            if target is None and tried:
                target = routed_pick(frozenset())
            return target

        abort = getattr(request, "abort", None)

        while True:
            now = time.monotonic()
            if abort is not None and abort.is_set():
                # the caller vanished (streaming disconnect): cancel
                # every in-flight attempt — wire-level, so the replica
                # frees the sequence's pages — and fail explicitly; a
                # request nobody will read must not keep decoding
                for a in attempts:
                    if not a.done:
                        self.client.cancel(a)
                    self._settle(a)
                # the disconnect metric is about STREAMING callers that
                # vanished; the tier attaches abort events to unary
                # requests too (a gateway kill fires them), and those
                # must not inflate the caller-disconnect signal
                if self.metrics and (
                    getattr(request, "on_tokens", None) is not None
                ):
                    self.metrics.inc("gateway_stream_disconnects_total")
                return DispatchOutcome(
                    "error", error="cancelled: caller disconnected",
                    attempts=n_attempts, hedged=hedged,
                )
            if now >= deadline:
                for a in attempts:
                    if not a.done:
                        self.client.cancel(a)
                    self._settle(a)
                if self.metrics:
                    self.metrics.inc("gateway_deadline_exceeded_total")
                return DispatchOutcome(
                    "timeout", error=f"deadline exceeded after "
                    f"{n_attempts} attempt(s): {last_error}",
                    attempts=n_attempts, hedged=hedged,
                )

            if not attempts:
                # nothing in flight — the ONE place attempts are opened
                # (first admission and every post-failure re-admission),
                # so the budget rules live in one place: charged only for
                # re-admissions, and only once a concrete replica exists
                # (a cluster mid-failover may have zero live replicas for
                # a cycle; polling while nobody is routable must not
                # drain the budget — the request waits, bounded by the
                # deadline above).
                if n_attempts > 0 and n_attempts >= policy.max_attempts:
                    if self.metrics:
                        self.metrics.inc("gateway_failures_total")
                    return DispatchOutcome(
                        "error", error=f"{n_attempts} attempt(s) failed: "
                        f"{last_error}",
                        attempts=n_attempts, hedged=hedged,
                    )
                candidate = pick_target()
                if candidate is None:
                    time.sleep(_POLL_S * 5)
                    continue
                if n_attempts > 0:
                    if not self.retry_budget.try_spend():
                        if self.metrics:
                            self.metrics.inc(
                                "gateway_retry_budget_exhausted_total"
                            )
                        return DispatchOutcome(
                            "error", error="retry budget exhausted: "
                            + last_error,
                            attempts=n_attempts, hedged=hedged,
                        )
                    if self.metrics:
                        self.metrics.inc("gateway_retries_total")
                    if trace is not None:
                        # the retry decision itself is a tree node: the
                        # re-admission after a failed attempt, with the
                        # error it is retrying past
                        trace.event(
                            "retry", attempt=n_attempts + 1,
                            after_error=last_error,
                        )
                tried.add(candidate.key)
                attempts.append(
                    self._submit(candidate, request, n_attempts + 1)
                )
                n_attempts += 1
                hedge_at = time.monotonic() + policy.hedge_after_s
                continue

            # seal-watch: ship pages sealed so far toward the handoff
            # target while the rest of the prefill still computes —
            # the streamed half of the disaggregation pipeline
            if self.stream_handoff and self.disaggregation:
                for a in list(attempts):
                    if not a.done and not getattr(a, "_handed_off", False):
                        self._stream_deltas(a, request, live)

            # post-prefill handoff: a sealed announcement means the
            # sequence is PARKED on a prefill-only replica — act on it
            # unconditionally (it decodes nowhere until this lands)
            for a in list(attempts):
                if (a.sealed.is_set() and not a.done
                        and not getattr(a, "_handed_off", False)):
                    self._do_handoff(a, request, live())

            winner = None
            for a in attempts:
                if a.wait(_POLL_S / max(len(attempts), 1)):
                    winner = a
                    break
            if winner is not None:
                res = winner.result()
                if res is not None and res.ok and not winner.cancelled:
                    for a in attempts:
                        if a is not winner:
                            if not a.done:
                                self.client.cancel(a)
                            if self.metrics:
                                self.metrics.inc("gateway_hedge_wasted_total")
                        self._settle(a)
                    return DispatchOutcome(
                        "ok", tokens=res.tokens, replica=winner.replica,
                        attempts=n_attempts, hedged=hedged,
                        handed_off=(
                            getattr(winner, "handoff_outcome", "") == "ok"
                        ),
                    )
                # failed (replica died / refused / cancelled): drop it;
                # if nothing else is in flight the empty-attempts branch
                # above owns the (budgeted) re-admission
                last_error = res.error if res else "unknown"
                attempts.remove(winner)
                self._settle(winner)
                continue

            # no completion yet: is the in-flight attempt straggling?
            # Pick the hedge target BEFORE spending the budget — a token
            # burned with nowhere to hedge to throttles future hedges
            # without ever issuing one.
            if (
                not hedged
                and not self.hedge_disabled
                and len(attempts) == 1
                and hedge_at is not None
                and now >= hedge_at
                # no_hedge: UNPINNED sampled streams (temperature > 0,
                # no request seed) never hedge — replicas do not emit
                # identical unpinned sampled streams, so a twin's
                # tokens could not be deduped coherently.  Greedy AND
                # seed-pinned sampled streams DO hedge: position-keyed
                # sample keys (or determinism) make every replica's
                # stream byte-identical, the StreamRelay dedups by
                # token index and the resume watermark fast-forwards
                # the twin, so tail latency is hedged exactly for the
                # requests users watch token by token.
                and not getattr(request, "no_hedge", False)
            ):
                target = routed_pick(frozenset(tried), hedge=True)
                if target is None:
                    hedge_at = None  # nowhere to hedge to; stop trying
                elif self.hedge_budget.try_spend():
                    tried.add(target.key)
                    attempts.append(
                        self._submit(target, request, n_attempts + 1,
                                     hedge=True)
                    )
                    hedged = True
                    if self.metrics:
                        self.metrics.inc("gateway_hedges_total")
                        if getattr(request, "on_tokens", None) is not None:
                            # a STREAMING hedge — only safe because the
                            # relay's prefix dedup keeps the caller's
                            # stream exactly-once
                            self.metrics.inc(
                                "gateway_stream_hedges_total"
                            )
                        if float(getattr(request, "temperature", 0.0)) > 0:
                            # a SAMPLED hedge can only be seed-pinned
                            # (unpinned sampled sets no_hedge) — count
                            # it so the determinism contract's payoff
                            # is visible on dashboards
                            self.metrics.inc("gateway_sampled_hedges_total")
                else:
                    hedge_at = None  # budget denied; stop re-checking


@dataclass
class DispatchOutcome:
    status: str          # "ok" | "error" | "timeout" | "rejected" (shed)
    tokens: List[int] = None         # type: ignore[assignment]
    replica: str = ""
    error: str = ""
    attempts: int = 0
    hedged: bool = False
    # served disaggregated: prefilled on one replica, decoded on another
    # (False covers co-located AND the handoff-fallback path, where the
    # prefill replica ended up decoding after a refused import)
    handed_off: bool = False

    def __post_init__(self) -> None:
        if self.tokens is None:
            self.tokens = []
