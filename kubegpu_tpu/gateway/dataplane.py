"""The cross-process data plane: replica HTTP serving + streaming client.

Until now the gateway's routing/failover/hedging machinery only ever met
a batcher in-process (``InMemoryReplicaClient``); in-cluster ``/readyz``
fail-safed at 503 because there was no wire to a real replica.  This
module is that wire, both ends:

**Replica side** — ``ReplicaServer`` wraps any batcher speaking the
incremental serving API (``submit``/``serve_step``/``cancel``/
``has_work``: the paged and dense continuous batchers, or SimBatcher)
behind a small HTTP endpoint, driven by ONE serving thread that owns the
batcher's ``serve_step`` loop (the in-process ``_ReplicaWorker`` shape,
behind sockets):

    POST /v1/submit   {"request_id", "prompt": [ints], "max_new_tokens",
                       "temperature", "session", "seed"?}
        → 200 text/event-stream (chunked): one ``tokens`` event per
          committed token batch — under the pipelined decode loop the
          host learns tokens at its one readback point, one step late,
          so each flush IS a commit point — then a terminal ``done``
          (full token list + the replica-side span dicts + the receive
          stamp) or ``error`` event.  ``: ping`` comment frames keep the
          socket honest while a sequence waits; a client that vanishes
          mid-stream fails the next write and its sequence is CANCELLED
          (pages freed) — disconnect ⇒ cancel.
    POST /v1/cancel   {"request_id"} → {"cancelled": bool}; wire-level
          cancel: the sequence's pages go back to the pool NOW, not when
          the stream times out.
    GET  /v1/state    advertised serving contract: tensor-parallel width
          (``replica_mesh``), slots, page economy (free/live/cached from
          the last ledger row), active streams; ``?ledger=K`` adds the
          last K ledger rows.
    GET  /healthz     liveness ("ok") — the registry's HTTP probe target.
    GET  /metrics     Prometheus text (``replica_http_*`` + whatever the
          batcher observed into the shared registry).

**Gateway side** — ``HttpReplicaClient`` implements the existing
``ReplicaClient`` interface over that protocol: ``submit`` returns an
``Attempt`` handle and streams on a reader thread (token deltas surface
through the request's optional ``on_tokens`` callback — the gateway's
SSE pass-through), connections are kept per replica and reused across
completed streams, ``cancel`` closes the stream socket AND posts a wire
cancel so the replica frees pages immediately, and per-attempt deadlines
(``request.deadline_s`` anchored at ``enqueued_at``) cancel on the wire
when they expire.  Trace context crosses the boundary in headers
(``X-Trace-Id``/``X-Span-Id``); the replica serves the request under its
OWN tracer and ships the finished span dicts back in the terminal event,
which the client grafts under the gateway's dispatch span
(``Tracer.graft``) — one tree, two processes.

Failure model mirrors the in-memory client: connection refusal and
mid-stream resets are attempt RESULTS (errors), never exceptions; a
replica leaving the registry's live set aborts its in-flight attempts so
failover re-dispatches the same cycle.
"""

from __future__ import annotations

import base64
import http.client
import json
import logging
import queue
import socket
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kubegpu_tpu.gateway.client import (
    Attempt,
    AttemptResult,
    ReplicaClient,
    _sniff_takes,
    _sniff_takes_trace,
    sim_stream_seed,
)
from kubegpu_tpu.utils.metrics import Metrics
from kubegpu_tpu.utils.tracing import SpanCtx, Tracer

log = logging.getLogger(__name__)

# SSE keepalive cadence: a stream with no token progress writes a ping
# comment this often, so a vanished client is detected within one frame
# (the write fails) instead of holding its sequence until some timeout
PING_INTERVAL_S = 0.2


def sse_event(event: str, payload: dict) -> bytes:
    """One SSE frame.  The ONE framing implementation for both ends of
    the wire (replica handler here, the gateway's streaming
    pass-through in server.py) — the schema must not drift apart."""
    return f"event: {event}\ndata: {json.dumps(payload)}\n\n".encode()


def write_chunk(wfile, data: bytes) -> None:
    """One HTTP/1.1 chunked-transfer frame (shared with server.py)."""
    wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
    wfile.flush()


def end_chunks(wfile) -> None:
    """The chunked-transfer terminator: the connection stays reusable."""
    wfile.write(b"0\r\n\r\n")
    wfile.flush()


def _connect(addr: str, timeout: float) -> http.client.HTTPConnection:
    """The ONE "host:port" parse + connection constructor (probe, state,
    wire cancel and the stream pool all route through it)."""
    host, _, port = addr.rpartition(":")
    return http.client.HTTPConnection(host, int(port), timeout=timeout)


def _int_or(value, default: int) -> int:
    """Defensive wire-field parse: a malformed numeric from a CLIENT
    must degrade, never raise on the serving thread."""
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# KV transfer payload codec (the EXPORT/IMPORT wire schema)
# ---------------------------------------------------------------------------
# A batcher-level transfer payload carries per-layer page arrays as host
# numpy; on the wire they become base64 of the raw bytes plus an explicit
# shape (dtype rides the payload's geometry).  The GATEWAY never decodes
# layers — it relays export→import opaquely — so only replica processes
# (which have jax's ml_dtypes for bfloat16) pay the codec.

def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax's extended dtypes (bfloat16 et al.)

        return np.dtype(getattr(ml_dtypes, name))


def _encode_pairs(pairs) -> list:
    return [
        {
            "k": base64.b64encode(
                np.ascontiguousarray(k).tobytes()
            ).decode("ascii"),
            "v": base64.b64encode(
                np.ascontiguousarray(v).tobytes()
            ).decode("ascii"),
            "shape": [int(d) for d in np.shape(k)],
        }
        for k, v in pairs
    ]


def _decode_pairs(entries, dtype) -> list:
    return [
        (
            np.frombuffer(
                base64.b64decode(e["k"]), dtype=dtype
            ).reshape(e["shape"]),
            np.frombuffer(
                base64.b64decode(e["v"]), dtype=dtype
            ).reshape(e["shape"]),
        )
        for e in entries
    ]


def encode_kv_payload(payload: dict) -> dict:
    """JSON-safe encoding of a KV transfer payload (identity on
    payloads without page arrays, e.g. SimBatcher's cursor-only ones).
    Schema v2 (quantized pools): ``layers`` carry int8 page bytes —
    HALF the wire per page of a bf16 pool — and a ``scales`` section
    carries the (pages, heads) float32 per-page per-head scales."""
    out = {
        k: v for k, v in payload.items() if k not in ("layers", "scales")
    }
    if "layers" in payload:
        out["layers"] = _encode_pairs(payload["layers"])
    if "scales" in payload:
        out["scales"] = _encode_pairs(payload["scales"])
    return out


def decode_kv_payload(wire: dict) -> dict:
    """The inverse: base64 page arrays back to host numpy.  The page
    arrays' dtype is the geometry's STORAGE format (``kv_dtype``, v2)
    — int8 for quantized pools — falling back to the compute ``dtype``
    for schema-1 payloads; scales are always float32."""
    out = {
        k: v for k, v in wire.items() if k not in ("layers", "scales")
    }
    if "layers" in wire:
        geom = wire["geometry"]
        dtype = _np_dtype(geom.get("kv_dtype") or geom["dtype"])
        out["layers"] = _decode_pairs(wire["layers"], dtype)
    if "scales" in wire:
        out["scales"] = _decode_pairs(wire["scales"], np.float32)
    return out


# ---------------------------------------------------------------------------
# Replica side
# ---------------------------------------------------------------------------

class _Stream:
    """One in-flight request's server-side state: the event queue its
    HTTP handler drains, and the incremental-emit watermark."""

    __slots__ = ("request_id", "seq", "q", "emitted", "t_recv", "trace",
                 "cancelled", "closed")

    def __init__(self, request_id: str, t_recv: float) -> None:
        self.request_id = request_id
        self.seq: Optional[int] = None
        self.q: "queue.Queue[tuple]" = queue.Queue()
        self.emitted = 0
        self.t_recv = t_recv
        self.trace: Optional[SpanCtx] = None
        self.cancelled = False
        self.closed = False


class ReplicaServingLoop:
    """The serving thread that owns the batcher: drains submissions and
    cancels, drives ``serve_step``, and pushes token-batch events into
    per-request stream queues.  Exactly one thread touches the batcher
    (the batchers are single-driver by design), so HTTP handler threads
    never race the decode loop."""

    def __init__(self, batcher, metrics: Optional[Metrics] = None,
                 tracer: Optional[Tracer] = None,
                 step_delay_s: float = 0.0,
                 fail_migration: bool = False,
                 role: str = "flex") -> None:
        self.batcher = batcher
        self.metrics = metrics
        # disaggregation role: a "prefill" replica runs chunked prefill
        # only — sequences PARK at seal instead of decoding, and the
        # stream announces a non-terminal ``sealed`` event so the
        # gateway can hand the sequence off to a decode replica
        self.role = role if role in ("prefill", "decode", "flex") else "flex"
        prefill_fn = getattr(batcher, "set_prefill_only", None)
        if prefill_fn is not None:
            prefill_fn(self.role == "prefill")
        # the replica's own tracer: every request serves under a local
        # root whose finished span dicts ride the terminal event back to
        # the gateway for grafting
        self.tracer = tracer if tracer is not None else Tracer(
            max_traces=64
        )
        self.step_delay_s = step_delay_s
        # chaos knob (worker --serve-http-fail-migration): an armed
        # replica refuses /v1/import — the soak's importer-refusal leg
        self.fail_migration = fail_migration
        self._takes_trace = _sniff_takes_trace(batcher)
        self._takes_stream_seed = _sniff_takes(
            batcher, "submit", "stream_seed"
        )
        self._takes_seed = _sniff_takes(batcher, "submit", "seed")
        # RLock: _finish mutates stream maps from both the serving
        # thread (already holding the condition's lock on the shutdown
        # path) and the flush path
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._inbox: deque = deque()        # (_Stream, payload dict)
        self._cancels: List[str] = []       # request ids
        self._evicted: List[_Stream] = []   # duplicate-id losers
        # control ops (export/import): closures run ON the serving
        # thread between steps — migration must never race serve_step
        self._ops: deque = deque()          # (fn, reply queue)
        self._streams: Dict[str, _Stream] = {}
        self._by_seq: Dict[int, _Stream] = {}
        self._next_seq = 0
        self.alive = True
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- handler-facing surface (any thread) -------------------------------
    def submit(self, payload: dict, t_recv: float) -> _Stream:
        st = _Stream(str(payload.get("request_id") or ""), t_recv)
        with self._cond:
            if not self.alive:
                st.q.put(("error", "replica shutting down", [], t_recv))
                st.closed = True
                return st
            # last-writer-wins on a duplicate id, like the batchers'
            # resubmit flow: the old stream errors out, the new one owns
            # the id (cancel routing needs one owner)
            old = self._streams.get(st.request_id)
            if old is not None and not old.closed:
                old.cancelled = True
                self._evicted.append(old)
            self._streams[st.request_id] = st
            self._inbox.append((st, payload))
            self._cond.notify()
        return st

    def cancel(self, request_id: str,
               stream: Optional[_Stream] = None) -> bool:
        """Cancel by request id.  ``stream`` pins the cancel to ONE
        stream object: a disconnect handler for an EVICTED (resubmitted)
        stream must not cancel the newer live stream that now owns the
        same id — its retry is healthy."""
        with self._cond:
            st = self._streams.get(request_id)
            if st is None or st.closed:
                return False
            if stream is not None and st is not stream:
                return False
            st.cancelled = True
            self._cancels.append(request_id)
            self._cond.notify()
            return True

    def active_streams(self) -> int:
        with self._lock:
            return sum(1 for s in self._streams.values() if not s.closed)

    # -- KV-page migration verbs (handler-facing; run on the loop) ---------
    def control(self, fn, timeout: float = 60.0):
        """Run a closure on the serving thread between steps; returns
        its value or re-raises its exception."""
        reply: "queue.Queue" = queue.Queue(1)
        with self._cond:
            if not self.alive:
                raise RuntimeError("replica shutting down")
            self._ops.append((fn, reply))
            self._cond.notify()
        ok, val = reply.get(timeout=timeout)
        if not ok:
            raise val
        return val

    def export_live(self, request_id: str, cursor: int = 0) -> dict:
        """Export + DETACH one live stream's sequence — the migration
        source half, atomic on the serving thread: the payload is
        captured, freshly-committed tokens flush to the stream, the
        sequence's pages free, and the stream ends with a ``migrated``
        terminal whose span dicts ship for the gateway-side graft.
        A nonzero ``cursor`` ships layers only for pages >= cursor —
        the streamed-handoff final hop, where earlier pages were
        already delta-staged (and possibly reclaimed) on the target."""
        def op():
            st = self._streams.get(request_id)
            if st is None or st.closed or st.seq is None:
                raise KeyError(f"no live stream {request_id!r}")
            if not hasattr(self.batcher, "export_pages"):
                raise ValueError(
                    "batcher does not speak the migration verbs"
                )
            payload = (self.batcher.export_pages(st.seq, cursor)
                       if cursor else self.batcher.export_pages(st.seq))
            self._flush({})   # the export drain may have committed tokens
            self.batcher.cancel(st.seq)
            self._finish(st, "error", "migrated")
            return payload

        return self.control(op)

    def export_sealed(self, stream) -> Optional[dict]:
        def op():
            fn = getattr(self.batcher, "export_sealed_chain", None)
            return fn(stream) if fn is not None else None

        return self.control(op)

    def import_live(self, st: _Stream, payload: dict,
                    trace_id: str = "", span_id: str = "0") -> None:
        """Resume a migrated sequence here: import the payload, register
        the stream under its request id (duplicate-id eviction like
        submit), and set the emit watermark past the tokens the exporter
        already streamed — the continuation streams only NEW tokens,
        while the terminal ``done`` carries the full authoritative
        list."""
        def op():
            if self.fail_migration:
                raise RuntimeError("migration refused (chaos knob)")
            if not hasattr(self.batcher, "import_pages"):
                raise ValueError(
                    "batcher does not speak the migration verbs"
                )
            seq = self._next_seq
            self._next_seq += 1
            root = None
            if self.tracer is not None:
                root = self.tracer.start_trace(
                    "replica_import", request_id=st.request_id,
                    remote_trace=str(trace_id or ""),
                    remote_span=_int_or(span_id, 0),
                )
            kwargs = {"trace": root} if (
                root is not None
                and _sniff_takes_trace(self.batcher, "import_pages")
            ) else {}
            try:
                self.batcher.import_pages(seq, payload, **kwargs)
            except Exception:
                if root is not None:
                    root.end(status="refused")
                raise
            old = self._streams.get(st.request_id)
            if old is not None and not old.closed:
                old.cancelled = True
                self._evicted.append(old)
            self._streams[st.request_id] = st
            st.seq = seq
            st.trace = root
            st.emitted = len(payload.get("tokens") or [])
            self._by_seq[seq] = st

        self.control(op)

    def import_sealed(self, payload) -> int:
        def op():
            if self.fail_migration:
                raise RuntimeError("migration refused (chaos knob)")
            fn = getattr(self.batcher, "import_sealed_chain", None)
            if fn is None:
                raise ValueError(
                    "batcher does not speak the migration verbs"
                )
            return fn(payload)

        return self.control(op)

    # -- streamed seal-time handoff ------------------------------------
    def export_delta(self, request_id: str, cursor: int) -> Optional[dict]:
        """Pages sealed since ``cursor`` for a live stream's sequence,
        without detaching anything — the seal-watch read.  None means
        nothing new yet (or the batcher doesn't stream)."""
        def op():
            st = self._streams.get(request_id)
            if st is None or st.closed or st.seq is None:
                raise KeyError(f"no live stream {request_id!r}")
            fn = getattr(self.batcher, "export_sealed_delta", None)
            return fn(st.seq, cursor) if fn is not None else None

        return self.control(op)

    def import_delta(self, payload) -> int:
        def op():
            if self.fail_migration:
                raise RuntimeError("delta import refused (chaos knob)")
            fn = getattr(self.batcher, "import_sealed_delta", None)
            if fn is None:
                raise ValueError(
                    "batcher does not speak the streaming verbs"
                )
            return fn(payload)

        return self.control(op)

    def reclaim(self, request_id: str, upto: int) -> int:
        """Release the first ``upto`` pages of a parked sequence — the
        importer acked their delta copies, so the originals go back to
        the pool and a queued prefill can admit DURING the handoff."""
        def op():
            st = self._streams.get(request_id)
            if st is None or st.closed or st.seq is None:
                raise KeyError(f"no live stream {request_id!r}")
            fn = getattr(self.batcher, "reclaim_handoff_pages", None)
            return fn(st.seq, upto) if fn is not None else 0

        return self.control(op)

    def set_role(self, role: str) -> bool:
        """Flip the replica's serving role at runtime (the fleet
        controller's ratio actuator).  Runs on the serving thread: the
        prefill-only flag must never flip mid-``serve_step``.  Leaving
        "prefill" unparks every sealed sequence so it resumes decoding
        locally — a collapse back to co-located loses nothing."""
        if role not in ("prefill", "decode", "flex"):
            return False

        def op():
            self.role = role
            fn = getattr(self.batcher, "set_prefill_only", None)
            if fn is not None:
                fn(role == "prefill")
            return True

        return bool(self.control(op))

    def state(self, ledger_limit: int = 0) -> dict:
        b = self.batcher
        active_streams = self.active_streams()
        out = {
            "tp": int(getattr(b, "tp", 1)),
            "role": self.role,
            "slots": getattr(b, "slots", None),
            "decode_page_cache": getattr(b, "decode_page_cache", "off"),
            # the RESOLVED sealing policy: gates the gateway's eager
            # sealed-export captures (no point round-tripping a replica
            # that never seals)
            "seals_decode": bool(getattr(b, "_seal_decode", False)),
            "active_streams": active_streams,
        }
        # the batcher's counter dict (admits, prefix hit tokens, sealed
        # pages, ...), JSON-coerced: multi-process harnesses assert
        # "the restored turn actually hit warm decode pages" through
        # the wire, the way in-process tests read batcher.stats
        stats = getattr(b, "stats", None)
        if isinstance(stats, dict):
            out["stats"] = {
                k: v for k, v in stats.items()
                if isinstance(v, (int, float, str, bool))
            }
        # the prefix-cache economy (cached chains, resident pages by
        # kind, hit/miss tokens per prompt|decode kind): the warmth
        # surface the PrefixLocalityRouter and the FleetController read
        # per replica — duck-typed, absent for batchers with no cache
        economy_fn = getattr(b, "prefix_cache_stats", None)
        if economy_fn is not None:
            try:
                out["prefix_cache"] = economy_fn()
            except Exception:  # noqa: BLE001 - state must always serve
                pass
        # the pool's declared storage format rides the contract surface:
        # the gateway can see a fleet's kv_dtype skew without reading
        # ledgers, and migration tooling can pre-check compatibility
        kv_dtype = getattr(b, "kv_dtype", None)
        if kv_dtype is not None:
            out["kv_dtype"] = kv_dtype
        rows_fn = getattr(b, "ledger_rows", None)
        if rows_fn is not None:
            rows = rows_fn(max(ledger_limit, 1))
            if rows:
                last = rows[-1]
                out["pages"] = {
                    "free": last.get("pages_free", 0),
                    "live": last.get("pages_live", 0),
                    "cached": last.get("pages_cached", 0),
                }
                # per-dtype byte economy (the quantized-pool capacity
                # audit): what the pool RESTS by storage format
                if "kv_dtype" in last:
                    out["pages"]["kv_dtype"] = last["kv_dtype"]
                    out["pages"]["kv_bytes"] = last.get(
                        "pool_kv_bytes", 0
                    )
                    out["pages"]["scale_bytes"] = last.get(
                        "pool_scale_bytes", 0
                    )
            if ledger_limit > 0:
                out["ledger"] = rows[-ledger_limit:]
        return out

    def stop(self) -> None:
        with self._cond:
            self.alive = False
            self._cond.notify()
        self._thread.join(timeout=5.0)

    # -- the serving thread ------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while (self.alive and not self._inbox and not self._cancels
                       and not self._ops and not self.batcher.has_work()):
                    self._cond.wait(0.05)
                if not self.alive:
                    # blocked control callers must not hang on a corpse
                    while self._ops:
                        _, reply = self._ops.popleft()
                        reply.put((False, RuntimeError(
                            "replica shutting down"
                        )))
                    # process death: close the batcher's spans FIRST
                    # (every live serve subtree gets its ``died`` retire,
                    # the way a dead pod ends its connections), so the
                    # error events that follow ship COMPLETE subtrees for
                    # the gateway-side graft — then error every stream
                    # (the HTTP handlers flush it if their sockets still
                    # stand)
                    shutdown = getattr(self.batcher, "trace_shutdown", None)
                    if shutdown is not None:
                        shutdown("replica server stopped")
                    for st in list(self._streams.values()):
                        if not st.closed:
                            self._finish(st, "error", "replica shutting down")
                    return
                for st in self._evicted:
                    if not st.closed:
                        if st.seq is not None:
                            self.batcher.cancel(st.seq)
                        self._finish(st, "error", "resubmitted")
                        if self.metrics is not None:
                            # a duplicate-id eviction IS a wire-level
                            # cancel (the catalog counts both flavors)
                            self.metrics.inc("replica_http_cancels_total")
                self._evicted.clear()
                while self._inbox:
                    st, payload = self._inbox.popleft()
                    if st.cancelled:
                        self._finish(st, "error", "cancelled")
                        continue
                    self._admit(st, payload)
                for rid in self._cancels:
                    st = self._streams.get(rid)
                    if st is None or st.closed:
                        continue
                    if st.seq is not None:
                        self.batcher.cancel(st.seq)
                    self._finish(st, "error", "cancelled")
                    if self.metrics is not None:
                        self.metrics.inc(
                            "replica_http_cancels_total"
                        )
                self._cancels.clear()
                while self._ops:
                    fn, reply = self._ops.popleft()
                    try:
                        reply.put((True, fn()))
                    except Exception as e:  # noqa: BLE001 - op result
                        reply.put((False, e))
            # decode OUTSIDE the lock: a slow step (real JAX dispatch)
            # must not block submission/cancel delivery
            finished = (
                self.batcher.serve_step() if self.batcher.has_work() else {}
            )
            self._flush(finished)
            # prefill-only parking: a sequence whose prompt pages just
            # sealed announces it on the stream (non-terminal event) so
            # the gateway's dispatcher can trigger the handoff
            drain = getattr(self.batcher, "drain_sealed", None)
            if drain is not None:
                for seq in drain():
                    st = self._by_seq.get(seq)
                    if st is not None and not st.closed:
                        st.q.put(("sealed",))
            if self.step_delay_s:
                time.sleep(self.step_delay_s)

    def _admit(self, st: _Stream, payload: dict) -> None:
        remaining = payload.get("deadline_s")
        if remaining is not None:
            try:
                remaining = float(remaining)
            except (TypeError, ValueError):
                remaining = None
        if remaining is not None and (
            time.monotonic() - st.t_recv >= remaining
        ):
            # shed-before-work, replica side: the request's remaining
            # deadline (shipped by the gateway) elapsed while it queued
            # in this loop's inbox — admitting it would burn prefill on
            # an answer nobody will wait for.  A counted, retryable
            # refusal; the gateway's own deadline path owns the caller-
            # facing terminal.
            if self.metrics is not None:
                self.metrics.inc("replica_http_expired_refusals_total")
            self._finish(
                st, "error",
                "deadline expired before admission (backpressure)",
            )
            return
        seq = self._next_seq
        self._next_seq += 1
        root = None
        if self.tracer is not None:
            # the replica-side root; the batcher's serve subtree nests
            # under it.  The REMOTE parent ids ride as attributes — the
            # client-side graft re-parents under the live dispatch span,
            # these are the audit trail
            root = self.tracer.start_trace(
                "replica_request", request_id=st.request_id,
                remote_trace=str(payload.get("trace_id") or ""),
                # defensive: a garbage X-Span-Id must not kill the
                # serving thread (it only annotates the audit trail)
                remote_span=_int_or(payload.get("span_id"), 0),
            )
        prompt = np.asarray(payload.get("prompt") or [], np.int32)
        kwargs = {"session_id": payload.get("session")}
        if self._takes_trace:
            kwargs["trace"] = root
        if self._takes_stream_seed:
            # request-deterministic mill streams (real batchers decode
            # deterministically from the prompt; the mill must too, or
            # hedge dedup / sibling retries would mix unrelated streams)
            kwargs["stream_seed"] = sim_stream_seed(prompt)
        if self._takes_seed and payload.get("seed") is not None:
            # seed-pinned sampling: the batcher derives sample keys
            # from (seed, absolute position), so this replica's stream
            # matches any other replica's for the same request
            kwargs["seed"] = int(payload["seed"])
        try:
            self.batcher.submit(
                seq,
                prompt,
                int(payload.get("max_new_tokens", 0)),
                float(payload.get("temperature", 0.0)),
                **kwargs,
            )
        except Exception as e:  # noqa: BLE001 - bad request is a result
            if root is not None:
                root.end(status="rejected")
            st.trace = root
            self._finish(st, "error", str(e))
            return
        st.seq = seq
        st.trace = root
        # resume watermark (hedged streaming / retry / tier failover):
        # the caller already holds this many tokens — decode from 0 as
        # always (greedy is deterministic), but EMIT only past the
        # watermark.  The terminal done still carries the full list.
        wm = max(0, _int_or(payload.get("watermark"), 0))
        if wm:
            st.emitted = wm
            if self.metrics is not None:
                self.metrics.inc(
                    "replica_stream_fastforward_tokens_total", wm
                )
        self._by_seq[seq] = st

    def _flush(self, finished: Dict[int, List[int]]) -> None:
        """Emit token deltas for live sequences (one event per committed
        batch — the pipelined loop's readback points) and terminal events
        for finished ones."""
        live = getattr(self.batcher, "live_tokens", None)
        if live is not None:
            for seq, toks in live().items():
                st = self._by_seq.get(seq)
                if st is not None and len(toks) > st.emitted:
                    delta = list(toks[st.emitted:])
                    st.emitted = len(toks)
                    st.q.put(("tokens", delta))
        for seq, toks in finished.items():
            st = self._by_seq.pop(seq, None)
            if st is None or st.closed:
                continue
            if len(toks) > st.emitted:
                st.q.put(("tokens", list(toks[st.emitted:])))
            self._finish(st, "done", list(toks))

    def _finish(self, st: _Stream, kind: str, payload) -> None:
        """Terminal event: close the replica-side root, collect the
        completed span dicts, and hand the handler everything it needs
        for the wire."""
        st.closed = True
        with self._lock:
            if self._streams.get(st.request_id) is st:
                del self._streams[st.request_id]
        if st.seq is not None:
            self._by_seq.pop(st.seq, None)
        spans: List[dict] = []
        if st.trace is not None:
            st.trace.end(status=kind)
            got = self.tracer.trace(st.trace.trace_id)
            if got is not None:
                spans = got
        st.q.put((kind, payload, spans, st.t_recv))


def make_replica_handler(loop: ReplicaServingLoop,
                         metrics: Optional[Metrics],
                         auth_token: Optional[str] = None):
    """``auth_token``: optional bearer token required on every ``/v1/*``
    verb — the serving surface moves KV bytes and cancels sequences, so
    once the endpoint is exposed beyond loopback (TLS on, multi-tenant
    cluster) it must not be callable by any pod that can reach the
    podIP.  ``/healthz`` and ``/metrics`` stay open: probes and scrapes
    are read-only and gating them would drain replicas on token skew
    (the scheduler/server.py discipline)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def setup(self):
            # TLS: the listening socket is wrapped with
            # do_handshake_on_connect=False, so the handshake happens
            # HERE, on this connection's own thread, under a deadline —
            # a silent client costs one worker thread for 10 s, never
            # the accept loop (scheduler/server.py's pattern)
            if hasattr(self.request, "do_handshake"):
                prev = self.request.gettimeout()
                self.request.settimeout(10.0)
                try:
                    self.request.do_handshake()
                finally:
                    self.request.settimeout(prev)
            super().setup()

        def log_message(self, fmt, *args):
            log.debug("replica http: " + fmt, *args)

        def _authorized(self, path: str) -> bool:
            if not auth_token or not path.startswith("/v1/"):
                return True
            import hmac

            sent = self.headers.get("Authorization", "")
            # constant-time compare: the token gates exactly the
            # callers a timing oracle would serve
            if hmac.compare_digest(sent, f"Bearer {auth_token}"):
                return True
            # the refused request's body was never read: close the
            # connection rather than let a pooling client's NEXT
            # request be parsed out of the stale body bytes
            self.close_connection = True
            self._send_json(
                401, {"error": "unauthorized (bearer token required)"}
            )
            return False

        def _read_json(self) -> Optional[dict]:
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                return json.loads(raw) if raw else {}
            except (ValueError, json.JSONDecodeError):
                return None

        def _send_json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # -- chunked streaming helpers (shared framing) -----------------
        def _chunk(self, data: bytes) -> None:
            write_chunk(self.wfile, data)

        def _chunk_end(self) -> None:
            end_chunks(self.wfile)

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if not self._authorized(path):
                return
            if metrics is not None:
                metrics.inc("replica_http_requests_total", verb="state"
                            if path == "/v1/state" else "get")
            if path == "/healthz":
                body = b"ok"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/metrics" and metrics is not None:
                body = metrics.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/v1/state":
                limit = 0
                for part in query.split("&"):
                    if part.startswith("ledger="):
                        try:
                            limit = max(0, int(part.split("=", 1)[1]))
                        except ValueError:
                            pass
                self._send_json(200, loop.state(ledger_limit=limit))
            else:
                self._send_json(404, {"error": f"no route {path}"})

        def do_POST(self):
            if not self._authorized(self.path):
                return
            if self.path == "/v1/cancel":
                if metrics is not None:
                    metrics.inc("replica_http_requests_total", verb="cancel")
                body = self._read_json()
                if body is None or not body.get("request_id"):
                    self._send_json(400, {"error": "request_id required"})
                    return
                ok = loop.cancel(str(body["request_id"]))
                self._send_json(200, {"cancelled": ok})
                return
            if self.path == "/v1/role":
                if metrics is not None:
                    metrics.inc("replica_http_requests_total", verb="role")
                body = self._read_json()
                role = str((body or {}).get("role") or "")
                if role not in ("prefill", "decode", "flex"):
                    self._send_json(
                        400, {"error": "role must be prefill|decode|flex"}
                    )
                    return
                loop.set_role(role)
                self._send_json(200, {"role": loop.role})
                return
            if self.path == "/v1/export":
                self._handle_export()
                return
            if self.path == "/v1/import":
                self._handle_import()
                return
            if self.path != "/v1/submit":
                self._send_json(404, {"error": f"no route {self.path}"})
                return
            t_recv = time.monotonic()
            if metrics is not None:
                metrics.inc("replica_http_requests_total", verb="submit")
            body = self._read_json()
            if body is None:
                self._send_json(400, {"error": "malformed JSON body"})
                return
            body.setdefault("trace_id", self.headers.get("X-Trace-Id", ""))
            body.setdefault("span_id", self.headers.get("X-Span-Id", "0"))
            st = loop.submit(body, t_recv)
            self._serve_stream(st)

        def _serve_stream(self, st: _Stream) -> None:
            """The SSE streaming tail shared by /v1/submit and a live
            /v1/import: chunked event stream, disconnect ⇒ cancel pinned
            to THIS stream object."""
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            if metrics is not None:
                metrics.set_gauge(
                    "replica_http_streams_active", loop.active_streams()
                )
            try:
                self._stream(st)
            except (BrokenPipeError, ConnectionResetError, OSError):
                # the client vanished mid-stream: its sequence must not
                # keep decoding into pages nobody will read — disconnect
                # IS a cancel (the wire-level page-freeing guarantee).
                # Pinned to THIS stream: an evicted stream's dead socket
                # must not cancel the id's newer owner
                if loop.cancel(st.request_id, stream=st):
                    if metrics is not None:
                        metrics.inc(
                            "replica_http_disconnect_cancels_total"
                        )
                self.close_connection = True
            finally:
                if metrics is not None:
                    metrics.set_gauge(
                        "replica_http_streams_active", loop.active_streams()
                    )

        # -- KV-page migration verbs ------------------------------------
        def _handle_export(self) -> None:
            """POST /v1/export — {"request_id"}: export + detach a LIVE
            sequence (its stream ends with a ``migrated`` terminal);
            {"stream": [ints]}: read-only sealed-chain capture.  The
            response is {"payload": <encoded or null>, "pages": n}."""
            if metrics is not None:
                metrics.inc("replica_http_requests_total", verb="export")
            body = self._read_json()
            if body is None:
                self._send_json(400, {"error": "malformed JSON body"})
                return
            t0 = time.monotonic()
            try:
                if body.get("request_id") and body.get("reclaim") is not None:
                    # early reclaim: the importer acked a delta, so the
                    # parked source releases those pages to its pool
                    n = loop.reclaim(
                        str(body["request_id"]), int(body["reclaim"])
                    )
                    self._send_json(200, {"reclaimed": n})
                    return
                if body.get("request_id") and body.get("delta"):
                    payload = loop.export_delta(
                        str(body["request_id"]),
                        int(body.get("cursor") or 0),
                    )
                    wire = (
                        encode_kv_payload(payload)
                        if payload is not None else None
                    )
                    out = json.dumps({"payload": wire}).encode()
                    if metrics is not None and payload is not None:
                        metrics.inc(
                            "replica_migrate_wire_bytes_total",
                            len(out), dir="export",
                        )
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                    return
                if body.get("request_id"):
                    payload = loop.export_live(
                        str(body["request_id"]),
                        int(body.get("cursor") or 0),
                    )
                elif body.get("stream") is not None:
                    payload = loop.export_sealed(
                        [int(t) for t in body["stream"]]
                    )
                else:
                    self._send_json(
                        400, {"error": "request_id or stream required"}
                    )
                    return
            except KeyError as e:
                self._send_json(404, {"error": str(e)})
                return
            except (ValueError, RuntimeError) as e:
                self._send_json(409, {"error": str(e)})
                return
            wire = (
                encode_kv_payload(payload) if payload is not None else None
            )
            n_pages = (
                len(payload.get("page_keys") or []) if payload else 0
            )
            out = json.dumps({"payload": wire, "pages": n_pages}).encode()
            if metrics is not None and payload is not None:
                metrics.observe(
                    "replica_migrate_seconds", time.monotonic() - t0,
                    dir="export",
                )
                metrics.inc(
                    "replica_migrate_pages_total", n_pages, dir="export"
                )
                metrics.inc(
                    "replica_migrate_wire_bytes_total", len(out),
                    dir="export",
                )
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        def _handle_import(self) -> None:
            """POST /v1/import — {"payload"}: sealed-chain cache warm
            (JSON response); +{"request_id"}: live import whose response
            IS the continuation SSE stream (tokens after the migration
            point; the terminal ``done`` carries the full list)."""
            if metrics is not None:
                metrics.inc("replica_http_requests_total", verb="import")
            t_recv = time.monotonic()
            wire_bytes = _int_or(self.headers.get("Content-Length"), 0)
            body = self._read_json()
            if body is None or not isinstance(body.get("payload"), dict):
                self._send_json(400, {"error": "payload required"})
                return
            try:
                payload = decode_kv_payload(body["payload"])
            except Exception as e:  # noqa: BLE001 - wire junk is a 400
                self._send_json(
                    400, {"error": f"undecodable payload: {e}"}
                )
                return
            t0 = time.monotonic()
            if payload.get("kind") == "delta":
                # stage a streamed-handoff delta in the prefix cache;
                # the {"staged": n} ack licenses the source's reclaim
                try:
                    n = loop.import_delta(payload)
                except (ValueError, RuntimeError) as e:
                    self._send_json(503, {"error": str(e)})
                    return
                if metrics is not None:
                    metrics.inc(
                        "replica_migrate_wire_bytes_total", wire_bytes,
                        dir="import",
                    )
                self._send_json(200, {"staged": n})
                return
            if not body.get("request_id"):
                try:
                    n = loop.import_sealed(payload)
                except (ValueError, RuntimeError) as e:
                    self._send_json(503, {"error": str(e)})
                    return
                if metrics is not None:
                    metrics.observe(
                        "replica_migrate_seconds",
                        time.monotonic() - t0, dir="import",
                    )
                    metrics.inc(
                        "replica_migrate_pages_total", n, dir="import"
                    )
                    metrics.inc(
                        "replica_migrate_wire_bytes_total", wire_bytes,
                        dir="import",
                    )
                self._send_json(200, {"imported": n})
                return
            st = _Stream(str(body["request_id"]), t_recv)
            try:
                loop.import_live(
                    st, payload,
                    trace_id=self.headers.get("X-Trace-Id", ""),
                    span_id=self.headers.get("X-Span-Id", "0"),
                )
            except (KeyError, ValueError) as e:
                self._send_json(409, {"error": str(e)})
                return
            except RuntimeError as e:
                self._send_json(503, {"error": str(e)})
                return
            if metrics is not None:
                metrics.observe(
                    "replica_migrate_seconds", time.monotonic() - t0,
                    dir="import",
                )
                metrics.inc(
                    "replica_migrate_pages_total",
                    len(payload.get("page_keys") or []), dir="import",
                )
                metrics.inc(
                    "replica_migrate_wire_bytes_total", wire_bytes,
                    dir="import",
                )
            self._serve_stream(st)

        def _stream(self, st: _Stream) -> None:
            while True:
                try:
                    ev = st.q.get(timeout=PING_INTERVAL_S)
                except queue.Empty:
                    self._chunk(b": ping\n\n")
                    continue
                kind = ev[0]
                if metrics is not None:
                    metrics.inc("replica_http_stream_events_total")
                if kind == "tokens":
                    self._chunk(sse_event("tokens", {"tokens": ev[1]}))
                    continue
                if kind == "sealed":
                    # non-terminal: the prompt's pages sealed on a
                    # prefill-only replica and the sequence is parked
                    # awaiting handoff — the stream stays open
                    self._chunk(sse_event("sealed", {}))
                    continue
                if kind == "done":
                    self._chunk(sse_event("done", {
                        "tokens": ev[1], "spans": ev[2], "t_recv": ev[3],
                    }))
                else:
                    self._chunk(sse_event("error", {
                        "error": ev[1], "spans": ev[2], "t_recv": ev[3],
                    }))
                self._chunk_end()
                return

    return Handler


class _ReplicaHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        log.debug("replica connection error from %s", client_address,
                  exc_info=True)


class ReplicaServer:
    """One replica's HTTP serving endpoint: the serving loop plus the
    threaded HTTP server in front of it.  ``listen`` port 0 picks an
    ephemeral port (tests, loopback soak); ``stop()`` ends the serving
    loop first (live streams flush an explicit error event) and then the
    listener."""

    def __init__(self, batcher, listen: Tuple[str, int] = ("127.0.0.1", 0),
                 metrics: Optional[Metrics] = None,
                 tracer: Optional[Tracer] = None,
                 step_delay_s: float = 0.0,
                 fail_migration: bool = False,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 auth_token: Optional[str] = None,
                 role: str = "flex") -> None:
        if bool(tls_cert) != bool(tls_key):
            # a half-configured pair must not come up silently as the
            # plain-HTTP endpoint the operator believes is encrypted —
            # checked BEFORE anything binds a socket, so the raise
            # leaks nothing
            raise ValueError(
                "tls_cert and tls_key must be given together"
            )
        self.metrics = metrics if metrics is not None else Metrics()
        self.loop = ReplicaServingLoop(
            batcher, metrics=self.metrics, tracer=tracer,
            step_delay_s=step_delay_s, fail_migration=fail_migration,
            role=role,
        )
        self.httpd = _ReplicaHTTPServer(
            listen,
            make_replica_handler(self.loop, self.metrics,
                                 auth_token=auth_token),
        )
        self.tls = bool(tls_cert and tls_key)
        if self.tls:
            # the replica endpoint streams KV bytes and serves the
            # migration verbs; exposed beyond loopback it gets the same
            # treatment as the extender's privileged verbs: TLS on the
            # wire (handshake deferred to the handler thread — see
            # Handler.setup) + bearer auth on /v1/*.  Plain HTTP stays
            # the default for loopback tests and single-tenant pods.
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket,
                server_side=True,
                do_handshake_on_connect=False,
            )
        self._thread: Optional[threading.Thread] = None

    @property
    def batcher(self):
        return self.loop.batcher

    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    @property
    def port(self) -> int:
        return self.address[1]

    @property
    def endpoint(self) -> str:
        host, port = self.address
        return f"{host}:{port}"

    def start(self) -> "ReplicaServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.loop.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Gateway side
# ---------------------------------------------------------------------------

class HttpReplicaClient(ReplicaClient):
    """``ReplicaClient`` over the replica HTTP protocol.

    Endpoint resolution: explicit ``set_endpoint(key, "host:port")``
    entries win (tests, loopback soak); otherwise ``resolver(key)``
    (in-cluster: the registry's discovered pod IP + ``default_port``).
    A replica with no resolvable endpoint fails submissions immediately
    with an unreachable RESULT, like the in-memory client.

    Connection reuse: completed streams return their ``HTTPConnection``
    to a small per-replica pool; errors and cancels discard it (the
    socket state is unknowable mid-stream)."""

    def __init__(
        self,
        endpoints: Optional[Dict[str, str]] = None,
        resolver: Optional[Callable[[str], Optional[str]]] = None,
        default_port: int = 8700,
        timeout_s: float = 5.0,
        metrics: Optional[Metrics] = None,
        tls_ca: Optional[str] = None,
        auth_token: Optional[str] = None,
    ) -> None:
        self.resolver = resolver
        self.default_port = default_port
        self.timeout_s = timeout_s
        self.metrics = metrics
        # transport security, matching the replica server's knobs:
        # tls_ca = PEM bundle to verify replica certs against (HTTPS to
        # every endpoint); auth_token = bearer sent on every request
        # (the replica gates /v1/*).  Both None = plain loopback HTTP.
        self.auth_token = auth_token
        self._ssl_ctx = None
        if tls_ca is not None:
            import ssl

            self._ssl_ctx = ssl.create_default_context(cafile=tls_ca)
        self._lock = threading.Lock()
        self._endpoints: Dict[str, str] = dict(endpoints or {})
        self._pool: Dict[str, List[http.client.HTTPConnection]] = {}
        # replica key -> in-flight attempts (sync_live aborts these the
        # cycle a replica drains, so failover re-dispatches immediately
        # instead of waiting out a dead socket)
        self._inflight: Dict[str, set] = {}
        # request_id -> completed ok deliveries (soak's exactly-once and
        # wasted-hedge accounting — the in-memory client's `decodes`)
        self.decodes: Dict[str, int] = {}
        self._stopped = False

    # -- transport ---------------------------------------------------------
    def _connect(self, addr: str, timeout: float):
        """The one connection constructor for this client: plain HTTP,
        or HTTPS against the configured CA when ``tls_ca`` was given."""
        if self._ssl_ctx is None:
            return _connect(addr, timeout)
        host, _, port = addr.rpartition(":")
        return http.client.HTTPSConnection(
            host, int(port), timeout=timeout, context=self._ssl_ctx
        )

    def _headers(self, extra: Optional[dict] = None) -> dict:
        headers = dict(extra or {})
        if self.auth_token:
            headers["Authorization"] = f"Bearer {self.auth_token}"
        return headers

    # -- endpoints ---------------------------------------------------------
    def set_endpoint(self, key: str, addr: str) -> None:
        with self._lock:
            self._endpoints[key] = addr

    def drop_endpoint(self, key: str) -> None:
        with self._lock:
            self._endpoints.pop(key, None)
            for conn in self._pool.pop(key, []):
                conn.close()

    def endpoint_for(self, key: str) -> Optional[str]:
        with self._lock:
            addr = self._endpoints.get(key)
        if addr is None and self.resolver is not None:
            try:
                addr = self.resolver(key)
            except Exception:  # noqa: BLE001 - resolution is best-effort
                addr = None
        return addr

    def replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._endpoints)

    def ready(self) -> bool:
        with self._lock:
            if self._stopped:
                return False
            return bool(self._endpoints) or self.resolver is not None

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            attempts = [a for s in self._inflight.values() for a in s]
            conns = [c for pool in self._pool.values() for c in pool]
            self._pool.clear()
        for conn in conns:
            conn.close()
        for attempt in attempts:
            self._abort(attempt, "client stopped")

    # -- registry subscription --------------------------------------------
    def sync_live(self, live) -> None:
        """A replica leaving the live set is its process dying: abort
        its in-flight attempts now (the socket may linger half-open) so
        failover re-routes this cycle, and drop its pooled sockets."""
        with self._lock:
            gone = [k for k in self._inflight if k not in live]
            attempts = [a for k in gone for a in self._inflight.get(k, ())]
            for k in [k for k in self._pool if k not in live]:
                for conn in self._pool.pop(k, []):
                    conn.close()
        for attempt in attempts:
            self._abort(attempt, f"replica {attempt.replica} left live set")

    # -- probes / advertisement -------------------------------------------
    def _addr_of(self, info) -> Optional[str]:
        addr = self.endpoint_for(getattr(info, "key", ""))
        if addr is None and getattr(info, "addr", None):
            addr = f"{info.addr}:{self.default_port}"
        return addr

    def probe(self, info) -> Tuple[bool, str]:
        """HTTP health probe for the registry (``ReplicaRegistry``'s
        ``probe=`` hook): GET /healthz on the replica's endpoint.  A
        replica the control plane believes healthy but whose serving
        process is gone must drain from the live set — this is what
        makes in-cluster ``/readyz`` REAL instead of fail-safe."""
        addr = self._addr_of(info)
        if addr is None:
            return False, "no data-plane endpoint (pod IP unknown)"
        conn = self._connect(addr, timeout=1.0)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            resp.read()
            if resp.status == 200:
                return True, ""
            return False, f"/healthz returned {resp.status}"
        except OSError as e:
            return False, f"/healthz unreachable: {e}"
        finally:
            conn.close()

    def _get_state(self, key: str, ledger: int = 0) -> Optional[dict]:
        addr = self.endpoint_for(key)
        if addr is None:
            return None
        conn = self._connect(addr, timeout=1.0)
        try:
            path = "/v1/state" + (f"?ledger={ledger}" if ledger else "")
            conn.request("GET", path, headers=self._headers())
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                return None
            return json.loads(resp.read())
        except (OSError, ValueError):
            return None
        finally:
            conn.close()

    def advertised(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for key in self.replicas():
            state = self._get_state(key)
            if state is not None:
                out[key] = {"tp": int(state.get("tp", 1))}
        return out

    def ledgers(self, limit: int = 32) -> Dict[str, List[dict]]:
        out: Dict[str, List[dict]] = {}
        for key in self.replicas():
            state = self._get_state(key, ledger=limit)
            if state is not None and state.get("ledger"):
                out[key] = state["ledger"]
        return out

    # -- KV-page migration -------------------------------------------------
    def inflight_on(self, replica_key: str) -> List[Attempt]:
        with self._lock:
            return list(self._inflight.get(replica_key, ()))

    def seals_decode(self, replica_key: str) -> bool:
        state = self._get_state(replica_key)
        return bool(state and state.get("seals_decode"))

    def _wire_export(self, addr: str, body: dict) -> Optional[dict]:
        """POST /v1/export; returns the (still-encoded) payload dict or
        None — the gateway relays it to /v1/import opaquely, so only
        replica processes pay the codec."""
        conn = self._connect(addr, timeout=self.timeout_s)
        try:
            conn.request(
                "POST", "/v1/export", json.dumps(body),
                self._headers({"Content-Type": "application/json"}),
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return None
            return json.loads(data).get("payload")
        except (OSError, ValueError):
            return None
        finally:
            conn.close()

    def export_sealed(self, replica_key: str, stream) -> Optional[dict]:
        addr = self.endpoint_for(replica_key)
        if addr is None:
            return None
        return self._wire_export(
            addr, {"stream": [int(t) for t in stream]}
        )

    def import_sealed(self, replica_key: str, payload) -> bool:
        addr = self.endpoint_for(replica_key)
        if addr is None or payload is None:
            return False
        conn = self._connect(addr, timeout=self.timeout_s)
        try:
            conn.request(
                "POST", "/v1/import", json.dumps({"payload": payload}),
                self._headers({"Content-Type": "application/json"}),
            )
            resp = conn.getresponse()
            resp.read()
            return resp.status == 200
        except (OSError, ValueError):
            return False
        finally:
            conn.close()

    # -- streamed seal-time handoff ------------------------------------
    def export_delta(self, attempt: Attempt, request,
                     cursor: int) -> Optional[dict]:
        """POST /v1/export {"delta": true}: pages sealed since
        ``cursor``, kept wire-encoded — like migrate's payload, the
        gateway relays deltas opaquely and only replicas pay the
        codec."""
        addr = self.endpoint_for(attempt.replica)
        if addr is None:
            return None
        return self._wire_export(addr, {
            "request_id": request.request_id,
            "delta": True, "cursor": int(cursor),
        })

    def import_delta(self, replica_key: str, payload) -> Optional[int]:
        addr = self.endpoint_for(replica_key)
        if addr is None or payload is None:
            return None
        conn = self._connect(addr, timeout=self.timeout_s)
        try:
            conn.request(
                "POST", "/v1/import", json.dumps({"payload": payload}),
                self._headers({"Content-Type": "application/json"}),
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return None
            staged = json.loads(data).get("staged")
            return int(staged) if staged is not None else None
        except (OSError, ValueError, TypeError):
            return None
        finally:
            conn.close()

    def reclaim(self, attempt: Attempt, request, upto: int) -> int:
        addr = self.endpoint_for(attempt.replica)
        if addr is None:
            return 0
        conn = self._connect(addr, timeout=self.timeout_s)
        try:
            conn.request(
                "POST", "/v1/export",
                json.dumps({"request_id": request.request_id,
                            "reclaim": int(upto)}),
                self._headers({"Content-Type": "application/json"}),
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return 0
            return int(json.loads(data).get("reclaimed") or 0)
        except (OSError, ValueError, TypeError):
            return 0
        finally:
            conn.close()

    def set_role(self, key: str, role: str) -> bool:
        """POST /v1/role: flip a replica's serving role at runtime (the
        fleet controller's ratio actuator, wire flavor)."""
        addr = self.endpoint_for(key)
        if addr is None:
            return False
        conn = self._connect(addr, timeout=2.0)
        try:
            conn.request(
                "POST", "/v1/role", json.dumps({"role": role}),
                self._headers({"Content-Type": "application/json"}),
            )
            resp = conn.getresponse()
            resp.read()
            return resp.status == 200
        except (OSError, ValueError):
            return False
        finally:
            conn.close()

    def migrate(self, attempt: Attempt, request, to_key: str,
                _between: Optional[Callable[[], None]] = None,
                fallback: bool = False, cursor: int = 0) -> bool:
        """Live migration over the wire: POST /v1/export on the source
        (which detaches the sequence — its stream ends ``migrated``,
        which the source's reader recognizes and leaves unresolved),
        re-home the attempt, then stream the continuation from POST
        /v1/import on the target.  The SAME attempt handle resolves
        with the full token list from the target; a refused or dead
        importer resolves it with an error so normal failover
        re-dispatches cold — graceful, never wrong.

        ``fallback=True`` (the disaggregation handoff contract): if the
        target refuses or dies BEFORE streaming, the held payload is
        re-imported into the SOURCE so the sequence resumes decode where
        it prefilled — counted, never a request error.  It also permits
        ``to_key == from_key``: detach-and-resume locally, the collapse
        path when no decode replica is available at all."""
        if attempt.done:
            return False
        from_key = attempt.replica
        from_addr = self.endpoint_for(from_key)
        to_addr = self.endpoint_for(to_key)
        if from_addr is None or to_addr is None or (
            from_key == to_key and not fallback
        ):
            return False
        trace = getattr(request, "trace", None)
        if not isinstance(trace, SpanCtx):
            trace = None
        # overhang_ok: a handoff's continuation (and its teardown) may
        # resolve after a hedge twin already closed the request root —
        # the same asynchrony the dispatch spans carry
        mspan = (
            trace.child("migrate", source=from_key, target=to_key,
                        overhang_ok=True)
            if trace is not None else None
        )
        attempt._migrating = True
        export_body = {"request_id": request.request_id}
        if cursor:
            # streamed handoff's final hop: layers below the cursor
            # were already delta-staged on the target
            export_body["cursor"] = int(cursor)
        wire = self._wire_export(from_addr, export_body)
        if wire is None:
            # nothing detached — or the export RESPONSE was lost after
            # the replica already detached.  Clear the flag first: a
            # "migrated" terminal arriving from now on resolves the
            # attempt as a plain error (failover re-dispatches cold).
            # If the reader ALREADY swallowed that terminal while the
            # flag was up (it records _migrated_terminal before checking
            # the flag — the handshake that closes the race), resolve
            # the attempt here: the sequence is detached and no
            # continuation is coming, and an unresolved attempt would
            # otherwise hang until the dispatcher's full deadline.
            attempt._migrating = False
            if getattr(attempt, "_migrated_terminal", False):
                attempt.finish(AttemptResult(
                    False,
                    error="migration export response lost: sequence "
                    "detached at the source",
                ))
            if self.metrics is not None:
                self.metrics.inc(
                    "gateway_migrations_total", outcome="export_failed"
                )
            if mspan is not None:
                mspan.end(outcome="export_failed")
            return False
        # re-home BEFORE any chaos can fire: if the source dies now,
        # sync_live must not abort an attempt it no longer owns
        with self._lock:
            self._inflight.setdefault(to_key, set()).add(attempt)
            bucket = self._inflight.get(from_key)
            if bucket is not None:
                bucket.discard(attempt)
        attempt.replica = to_key
        if fallback:
            # src==dst (the collapse rung's local unpark) crossed no
            # wire: it must not read as a disaggregated handoff
            attempt.handoff_outcome = (
                "fallback" if to_key == from_key else "ok"
            )
        if _between is not None:
            _between()   # fault injection: kill-mid-migration schedules
        t = threading.Thread(
            target=self._run_attempt,
            args=(attempt, request, to_addr, to_key),
            kwargs={
                "import_payload": wire,
                "fallback": (from_key, from_addr) if fallback else None,
            },
            daemon=True,
        )
        t.start()
        if self.metrics is not None:
            self.metrics.inc("gateway_migrations_total", outcome="ok")
        if mspan is not None:
            mspan.end(
                outcome="ok", pages=len(wire.get("page_keys") or [])
            )
        return True

    # -- ReplicaClient -----------------------------------------------------
    def submit(self, replica_key: str, request) -> Attempt:
        attempt = Attempt(replica_key, request.request_id)
        attempt.request = request
        addr = self.endpoint_for(replica_key)
        with self._lock:
            stopped = self._stopped
        if addr is None or stopped:
            attempt.finish(AttemptResult(
                False, error=f"replica {replica_key} unreachable"
            ))
            return attempt
        with self._lock:
            self._inflight.setdefault(replica_key, set()).add(attempt)
        t = threading.Thread(
            target=self._run_attempt,
            args=(attempt, request, addr, replica_key),
            daemon=True,
        )
        t.start()
        return attempt

    def cancel(self, attempt: Attempt) -> None:
        attempt.cancelled = True
        # wire-level cancel FIRST (frees the replica's pages even if the
        # stream socket lingers), then tear the stream down locally
        threading.Thread(
            target=self._wire_cancel,
            args=(attempt.replica, attempt.request_id),
            daemon=True,
        ).start()
        self._abort(attempt, "cancelled")

    # -- internals ---------------------------------------------------------
    def _abort(self, attempt: Attempt, error: str) -> None:
        attempt.finish(AttemptResult(False, error=error))
        conn = getattr(attempt, "_stream_conn", None)
        if conn is not None:
            try:
                conn.close()  # unblocks the reader thread mid-read
            except OSError:
                pass

    def _wire_cancel(self, replica_key: str, request_id: str) -> None:
        addr = self.endpoint_for(replica_key)
        if addr is None:
            return
        conn = self._connect(addr, timeout=2.0)
        try:
            conn.request(
                "POST", "/v1/cancel",
                json.dumps({"request_id": request_id}),
                self._headers({"Content-Type": "application/json"}),
            )
            conn.getresponse().read()
        except OSError:
            pass  # the replica may already be gone; its death frees pages
        finally:
            conn.close()

    def _checkout(self, key: str, addr: str) -> http.client.HTTPConnection:
        with self._lock:
            pool = self._pool.get(key)
            if pool:
                return pool.pop()
        return self._connect(addr, timeout=self.timeout_s)

    def _checkin(self, key: str, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if not self._stopped:
                self._pool.setdefault(key, []).append(conn)
                return
        conn.close()

    def _settle(self, attempt: Attempt, replica_key: str) -> None:
        # keyed by the replica THIS reader served, not attempt.replica:
        # a migration re-homes the attempt to its target mid-flight, and
        # the source's reader settling afterwards must not evict it from
        # the target's bucket
        with self._lock:
            bucket = self._inflight.get(replica_key)
            if bucket is not None:
                bucket.discard(attempt)
                if not bucket:
                    self._inflight.pop(replica_key, None)

    def _deadline_of(self, request) -> Optional[float]:
        deadline_s = getattr(request, "deadline_s", None)
        if deadline_s is None:
            return None
        anchor = getattr(request, "enqueued_at", 0.0) or time.monotonic()
        return anchor + deadline_s

    def _rescue(self, attempt: Attempt, request, import_payload,
                fallback) -> bool:
        """The handoff fallback: the decode-side import refused or died
        BEFORE streaming — re-import the held payload into the SOURCE
        replica so the sequence resumes decode where it prefilled.
        Counted, never a request error.  Runs the continuation inline on
        this reader thread (fallback=None below: one rescue, no loop)."""
        if import_payload is None or fallback is None or attempt.done:
            return False
        fb_key, fb_addr = fallback
        with self._lock:
            bucket = self._inflight.get(attempt.replica)
            if bucket is not None:
                bucket.discard(attempt)
            self._inflight.setdefault(fb_key, set()).add(attempt)
        attempt.replica = fb_key
        attempt.handoff_outcome = "fallback"
        if self.metrics is not None:
            self.metrics.inc(
                "gateway_migrations_total", outcome="fallback"
            )
        self._run_attempt(
            attempt, request, fb_addr, fb_key,
            import_payload=import_payload,
        )
        return True

    def _run_attempt(self, attempt: Attempt, request, addr: str,
                     replica_key: str,
                     import_payload: Optional[dict] = None,
                     fallback: Optional[Tuple[str, str]] = None) -> None:
        """Reader thread: stream the attempt to completion.  The
        terminal event's span dicts are grafted into the gateway's trace
        BEFORE the attempt resolves, so the winner's tree is complete
        when the dispatcher records the result.  With
        ``import_payload``, the attempt is a migration CONTINUATION:
        POST /v1/import carries the exported payload and the stream
        resumes mid-sequence on the target replica; ``fallback``
        ((key, addr) of the SOURCE) re-imports there if the target
        refuses or dies before streaming."""
        conn = self._checkout(replica_key, addr)
        trace = getattr(request, "trace", None)
        if not isinstance(trace, SpanCtx):
            trace = None
        deadline = self._deadline_of(request)
        reusable = False
        streaming = False
        try:
            if import_payload is not None:
                path = "/v1/import"
                body = json.dumps({
                    "request_id": request.request_id,
                    "payload": import_payload,
                })
                attempt.handoff_wire_bytes = len(body)
            else:
                path = "/v1/submit"
                payload = {
                    "request_id": request.request_id,
                    "prompt": [int(t) for t in request.prompt],
                    "max_new_tokens": int(request.max_new_tokens),
                    "temperature": float(
                        getattr(request, "temperature", 0.0)
                    ),
                    "session": getattr(request, "session", None),
                }
                if getattr(request, "seed", None) is not None:
                    payload["seed"] = int(request.seed)
                wm = int(getattr(request, "resume_watermark", 0) or 0)
                if wm > 0:
                    # hedge/retry fast-forward: the replica emits only
                    # past the caller's delivered prefix; stream_base
                    # tells the relay where this attempt's deltas start
                    payload["watermark"] = wm
                    attempt.stream_base = wm
                if deadline is not None:
                    # remaining deadline rides the wire: the replica can
                    # refuse a DOOMED admission (one that aged past its
                    # budget in the serving loop's inbox) before burning
                    # prefill compute on it — the shed-before-work rule,
                    # enforced on both ends
                    payload["deadline_s"] = max(
                        0.0, deadline - time.monotonic()
                    )
                body = json.dumps(payload)
            headers = self._headers({"Content-Type": "application/json"})
            if trace is not None:
                headers["X-Trace-Id"] = trace.trace_id
                headers["X-Span-Id"] = str(trace.span_id)
            attempt._stream_conn = conn
            t_send = time.monotonic()
            conn.request("POST", path, body, headers)
            resp = conn.getresponse()
            if resp.status != 200:
                err = resp.read()[:200].decode(errors="replace")
                if import_payload is not None and self.metrics is not None:
                    self.metrics.inc(
                        "gateway_migrations_total", outcome="import_refused"
                    )
                if self._rescue(attempt, request, import_payload, fallback):
                    return
                attempt.finish(AttemptResult(
                    False, error=f"replica {replica_key} refused "
                    f"({resp.status}): {err}"
                ))
                return
            streaming = True
            reusable = self._read_stream(
                attempt, request, resp, trace, t_send, deadline
            )
        except socket.timeout:
            if not streaming and self._rescue(
                attempt, request, import_payload, fallback
            ):
                return
            self._wire_cancel(replica_key, request.request_id)
            attempt.finish(AttemptResult(
                False, error="attempt timed out on the wire"
            ))
        except (OSError, ValueError, AttributeError,
                http.client.HTTPException) as e:
            # AttributeError: http.client reading a connection that
            # cancel() closed under us (fp already torn down).
            # The rescue only fires pre-stream: once the target has
            # emitted tokens, a re-import would replay them — normal
            # failover owns that path.
            if not streaming and self._rescue(
                attempt, request, import_payload, fallback
            ):
                return
            attempt.finish(AttemptResult(
                False,
                error=f"replica {replica_key} connection failed: {e}",
            ))
        finally:
            attempt._stream_conn = None
            self._settle(attempt, replica_key)
            if reusable:
                self._checkin(replica_key, conn)
            else:
                conn.close()

    def _read_stream(self, attempt: Attempt, request, resp, trace,
                     t_send: float, deadline: Optional[float]) -> bool:
        """Parse SSE events off the response.  Returns True when the
        stream terminated cleanly (connection reusable)."""
        on_tokens = getattr(request, "on_tokens", None)
        tokens: List[int] = []
        event, data = "", ""
        terminal = None
        while True:
            if (terminal is None and deadline is not None
                    and time.monotonic() >= deadline):
                # per-attempt deadline: cancel ON THE WIRE so the
                # replica frees the sequence's pages now — an expired
                # attempt must not keep decoding server-side.  Not
                # enforced during the post-terminal drain-to-EOF: the
                # request already resolved, and a spurious cancel there
                # would only discard a cleanly-reusable connection
                self._wire_cancel(attempt.replica, request.request_id)
                attempt.finish(AttemptResult(
                    False, error="attempt deadline expired"
                ))
                return False
            line = resp.readline()
            if not line:
                if terminal is not None:
                    return True  # chunked terminator after the event
                attempt.finish(AttemptResult(
                    False,
                    error=f"replica {attempt.replica} closed mid-stream",
                ))
                return False
            line = line.strip().decode(errors="replace")
            if terminal is not None:
                continue  # drain to EOF for connection reuse
            if line.startswith(":"):
                continue  # keepalive ping
            if line.startswith("event:"):
                event = line[6:].strip()
                continue
            if line.startswith("data:"):
                data = line[5:].strip()
                continue
            if line:
                continue
            # blank line: dispatch the buffered event
            if not event:
                continue
            try:
                payload = json.loads(data) if data else {}
            except json.JSONDecodeError:
                payload = {}
            if event == "sealed":
                # non-terminal: the prompt's pages sealed on a
                # prefill-only replica — wake the dispatcher's handoff
                attempt.sealed.set()
            elif event == "tokens":
                delta = payload.get("tokens") or []
                tokens.extend(delta)
                if on_tokens is not None and delta:
                    try:
                        on_tokens(attempt, list(delta))
                    except Exception:  # noqa: BLE001 - sink is advisory
                        log.exception("on_tokens callback failed")
            elif event in ("done", "error"):
                terminal = event
                self._graft(trace, payload, t_send)
                if event == "done":
                    final = payload.get("tokens")
                    result = AttemptResult(
                        True,
                        tokens=list(final) if final is not None else tokens,
                    )
                    if attempt.finish(result) and result.ok:
                        with self._lock:
                            self.decodes[request.request_id] = (
                                self.decodes.get(request.request_id, 0) + 1
                            )
                elif str(payload.get("error", "")) == "migrated":
                    # the exporter detached this sequence.  Record that
                    # FIRST (migrate()'s export-failure path checks it:
                    # a lost /v1/export response must still resolve the
                    # attempt — see the flag handshake there), THEN
                    # decide: mid-migration the import continuation owns
                    # the attempt's resolution, so leave it unresolved
                    # (the exporter's spans were still grafted above and
                    # the drain below leaves the connection reusable);
                    # otherwise surface it as a plain error so failover
                    # re-dispatches cold.
                    attempt._migrated_terminal = True
                    if not attempt._migrating:
                        attempt.finish(AttemptResult(
                            False, error="migrated"
                        ))
                else:
                    attempt.finish(AttemptResult(
                        False, error=str(payload.get("error", "error"))
                    ))
            event, data = "", ""

    def _graft(self, trace, payload: dict, t_send: float) -> None:
        """Stitch the replica-side spans under the gateway's dispatch
        span.  The offset maps the replica's monotonic clock onto ours:
        anchored at send time, so the whole remote subtree lands inside
        the dispatch span's window (network time on either side only
        widens the containment margin)."""
        if trace is None:
            return
        spans = payload.get("spans") or []
        t_recv = payload.get("t_recv")
        if not spans or t_recv is None:
            return
        try:
            trace.tracer.graft(trace, spans, t_send - float(t_recv))
        except Exception:  # noqa: BLE001 - tracing must never break serving
            log.exception("span graft failed")
