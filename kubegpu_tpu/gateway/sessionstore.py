"""Crash-durable session-KV insurance: the store behind the gateway tier.

PR 12's tier shared ONE in-process ``SessionKVStore`` instance, which
models the contract but not the deployment: sealed-KV failover insurance
captured by a gateway pod died with that pod, so ``deploy/gateway.yaml
replicas: 2`` was a deployment we claimed but never actually ran.  This
module externalizes the store so the insurance survives gateway death:

- ``SessionStoreBackend`` — the storage interface ``SessionKVStore``
  runs over.  Every op returns a ``StoreResult`` whose status names the
  failure mode (``absent`` / ``expired`` / ``conflict`` /
  ``unreachable``), because the CALLER's contract is graceful
  degradation: any store trouble resolves as a counted cold prefill
  (``gateway_session_store_degraded_total{reason}``), never a request
  error.
- ``InProcessStoreBackend`` — the default backend (the PR 12 behavior:
  one dict shared by the tier) AND the storage engine behind the HTTP
  server, so the two cannot drift: versioned compare-and-swap puts,
  per-session lease/TTL, the 256 MB byte-bounded payload LRU (oldest
  payloads drop, their tiny stream records stay — those sessions
  degrade to cold by design).
- ``StoreServer`` — the standalone HTTP store (``python -m
  kubegpu_tpu.gateway.sessionstore``): one small pod any number of
  gateway pods share.  ``/healthz`` + ``/metrics`` like every other
  endpoint in this repo.
- ``HttpStoreClient`` — the gateway-side backend over that protocol:
  per-op deadlines, bounded retry with exponential backoff + jitter
  (the PR 11 probe-backoff shape, injectable clock), and a CIRCUIT
  BREAKER so a dead store costs one fast-fail per op, not a deadline
  per request — with the store down, serving degrades to cold prefill
  at full speed.
- ``SessionKVStore`` — the gateway's insurance ledger, now pluggable:
  captures are written through ASYNCHRONOUSLY off the result path
  (bounded queue, drop-oldest — capture is insurance, never
  admission-blocking) and ``restore_for`` reads through at dispatch.

Why compare-and-swap: two gateways can race a capture for the same
session (the tier routes any session through any gateway, and a sibling
retry re-homes mid-conversation).  A capture exports the replica's
sealed chain for the stream it READ at version v; by the time the
payload comes back, a newer turn may have superseded the entry.  The
put carries ``if_version=v`` so the stale capture LOSES (counted, not
retried — the newer turn's own capture is already queued) instead of
interleaving an old seal over a newer one.

Wire protocol (JSON bodies; payloads are relayed opaquely — the store
never decodes KV bytes):

    GET    /v1/session/<id>       -> 200 {"entry", "version"}
                                     404 {"reason": "absent"|"lease_expired"}
    PUT    /v1/session/<id>       {"entry", "if_version": v|null,
                                   "lease_s": s|null}
                                  -> 200 {"version"} | 409 (CAS conflict)
    DELETE /v1/session/<id>       -> 200 {"deleted": bool}
    GET    /v1/sessions?replica=k -> 200 {"sessions": [...]}
    POST   /v1/mark               {"replica": k} | {"live": [...]}
                                  -> 200 {"marked": n}   (lost-marking)
    POST   /v1/chaos              (only with --chaos) {"conflicts": n} |
                                  {"expire_all": true}
    GET    /healthz, /metrics

Prefix-tier namespace (PR 16 — ``gateway/prefixtier.py``): sealed
chains keyed by CUMULATIVE CONTENT HASH, a key class distinct from
session leases.  Prefixes are immortal-while-hot: every probe hit or
fetch renews their (separate) TTL and bumps popularity, and eviction
under the prefix byte budget is popularity-weighted LRU — the coldest
entry goes first, a hot system prompt never does.  Payload BYTES are
deduplicated store-wide by content hash with refcounted references
(shared between the session and prefix namespaces), so a prefix
published by N replicas — or a chain captured by N sessions — rests
once:

    PUT    /v1/prefix/<chain_key> {"entry": {"payload", "page_keys",
                                   "pages"}}
                                  -> 200 {"stored": bool, "refs": n}
    POST   /v1/prefix/probe       {"keys": [k0..kn]}  (metadata-first)
                                  -> 200 {"chain", "match_pages",
                                          "pages"} | 404
    GET    /v1/prefix/<chain_key> (?meta=1)
                                  -> 200 {"entry"} | 404
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import logging
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple
from urllib.parse import parse_qs, quote, unquote

from kubegpu_tpu.utils.metrics import Metrics

log = logging.getLogger(__name__)

DEGRADE_REASONS = ("unreachable", "cas_conflict", "lease_expired")


class StoreResult(NamedTuple):
    """One store op's outcome.  ``status``:

    - ``ok``          — op applied; ``entry``/``version`` meaningful
    - ``absent``      — no such session (not an error)
    - ``expired``     — the session's lease lapsed (entry dropped)
    - ``conflict``    — a CAS put lost its race (someone wrote version+1)
    - ``unreachable`` — the store could not be reached (or the breaker
      fast-failed); the caller degrades to cold, never errors
    """

    status: str
    entry: Optional[dict] = None
    version: int = 0


class SessionStoreBackend:
    """Storage interface for ``SessionKVStore``.  An entry is a plain
    dict ``{"replica", "stream", "payload", "lost"}`` — the backend
    wraps it with a monotonically increasing per-session version and an
    optional lease."""

    def get(self, session: str, meta: bool = False) -> StoreResult:
        """``meta=True`` strips the (potentially multi-megabyte) KV
        payload from the returned entry, adding ``payload_present``
        instead — the dispatch hot path's no-op check and the capture's
        read-modify-write both need only metadata."""
        raise NotImplementedError

    def put(self, session: str, entry: dict,
            if_version: Optional[int] = None) -> StoreResult:
        """``if_version=None`` is an unconditional overwrite (a new turn
        supersedes the old entry); an integer makes the put
        compare-and-swap against that version."""
        raise NotImplementedError

    def delete(self, session: str) -> StoreResult:
        raise NotImplementedError

    def sessions_on(self, replica_key: str) -> Optional[List[str]]:
        """Sessions homed on one replica, or None when unreachable."""
        raise NotImplementedError

    def mark_lost(self, replica_key: str) -> bool:
        """Mark every entry homed on ``replica_key`` lost (drain/death)."""
        raise NotImplementedError

    def sync_live(self, live) -> bool:
        """Mark every entry whose replica is NOT in ``live`` lost."""
        raise NotImplementedError

    # -- prefix-tier namespace (PR 16) ------------------------------------
    def put_prefix(self, chain_key: str, entry: dict) -> StoreResult:
        """Publish a sealed chain under its cumulative content hash.
        ``entry``: ``{"payload", "page_keys", "pages"}``.  Idempotent —
        re-publishing an existing chain bumps its popularity instead of
        storing a duplicate."""
        raise NotImplementedError

    def probe_prefix(self, keys: List[str]) -> StoreResult:
        """Metadata-first: the LONGEST stored chain sharing a prefix
        with the prompt whose cumulative page keys are ``keys`` (in
        order).  Cumulative hashing makes this a point lookup per key,
        walked longest-first.  ``ok`` entries carry ``{"chain",
        "match_pages", "pages"}`` and the hit bumps popularity +
        renews the TTL (immortal-while-hot)."""
        raise NotImplementedError

    def get_prefix(self, chain_key: str, meta: bool = False) -> StoreResult:
        raise NotImplementedError

    def healthy(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# In-process backend (the default, and the HTTP server's engine)
# ---------------------------------------------------------------------------

def payload_bytes(payload) -> int:
    """Approximate retained bytes of a KV payload — host-numpy layers
    (in-memory lane) or base64 strings (wire lane).  Quantized
    (schema-v2) payloads carry a ``scales`` section next to their int8
    ``layers``; both count, or the byte budget would silently
    under-charge every quantized session."""
    if not isinstance(payload, dict):
        return 0
    total = 0
    for section in ("layers", "scales"):
        for entry in payload.get(section) or []:
            if isinstance(entry, dict):      # encoded wire payload
                total += len(entry.get("k") or "")
                total += len(entry.get("v") or "")
            else:                            # (k, v) host arrays
                for arr in entry:
                    total += getattr(arr, "nbytes", 0)
    return total


def payload_key(payload) -> Optional[str]:
    """Content address of a sealed-chain payload, REPRESENTATION-
    INDEPENDENT: the page keys are already cumulative content hashes of
    the token stream (PR 5), so hashing them — plus the geometry, which
    distinguishes pools that hold the same tokens in different shapes
    (kv_dtype, page size, tp) — identifies the payload bytes whether
    the layers arrived as host numpy or base64 wire strings.  ``None``
    for payloads that carry no page keys (nothing to dedup by)."""
    if not isinstance(payload, dict):
        return None
    keys = payload.get("page_keys")
    if not keys:
        return None
    h = hashlib.sha256()
    for k in keys:
        h.update(str(k).encode())
    h.update(json.dumps(
        payload.get("geometry") or {}, sort_keys=True, default=str
    ).encode())
    return h.hexdigest()


class InProcessStoreBackend(SessionStoreBackend):
    """Versioned, leased, byte-bounded session-entry map.

    One implementation serves both deployments: the tier's default
    in-process store (PR 12 semantics plus CAS versions) and the engine
    inside ``StoreServer`` — so the equivalence the tests assert
    (HTTP-vs-in-process, same capture/restore outcomes) holds by
    construction, not by parallel maintenance.

    ``lease_s``: entries expire that long after their last put (every
    put renews).  ``None`` = no expiry (the in-process default — the
    store dies with the process anyway).  The clock is injectable for
    fake-clock lease tests."""

    def __init__(self, max_sessions: int = 4096,
                 max_payload_bytes: int = 256 << 20,
                 lease_s: Optional[float] = None,
                 max_prefix_bytes: int = 256 << 20,
                 prefix_lease_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 metrics: Optional[Metrics] = None) -> None:
        self.max_sessions = max_sessions
        self.max_payload_bytes = max_payload_bytes
        self.lease_s = lease_s
        self.max_prefix_bytes = max_prefix_bytes
        # the prefix TTL is a SEPARATE eviction class from session
        # leases: renewed on every probe hit / fetch / re-publish, so a
        # hot prefix is immortal while traffic touches it and only a
        # genuinely cold one ages out
        self.prefix_lease_s = prefix_lease_s
        self.clock = clock
        self.metrics = metrics
        self._lock = threading.Lock()
        # session -> {"entry", "version", "expires", "bytes", "pkey"}
        self._records: "OrderedDict[str, dict]" = OrderedDict()
        self._payload_bytes = 0
        # content-addressed payload table: identical payload bytes rest
        # ONCE no matter how many sessions captured them or how many
        # replicas published them as a prefix.  pkey ->
        # {"payload", "bytes", "srefs", "prefs"} — per-namespace
        # refcounts so each budget charges a payload exactly once while
        # ANY of its refs live
        self._payloads: Dict[str, dict] = {}
        # chain_key -> {"pkey", "pages", "page_keys", "hits", "expires",
        # "last"} — the prefix namespace, popularity-weighted
        self._prefixes: "OrderedDict[str, dict]" = OrderedDict()
        # page_key -> set of chain keys whose chain contains that page:
        # the probe's point-lookup index (cumulative hashing means a
        # page-key match IS a shared-prefix match up to that page)
        self._page_index: Dict[str, set] = {}
        self._prefix_bytes = 0
        self._prefix_seq = 0
        # chaos knobs (soak/tests): fail the next N puts with a CAS
        # conflict; force-expire every lease
        self.force_conflicts = 0

    # -- internals ---------------------------------------------------------
    def _expired_locked(self, rec: dict) -> bool:
        return rec["expires"] is not None and self.clock() >= rec["expires"]

    def _payload_ref_locked(self, pkey: str, payload, ns: str):
        """Reference ``payload`` under ``pkey`` from namespace ``ns``
        (``srefs`` = sessions, ``prefs`` = prefixes); returns the
        CANONICAL payload object so duplicate bytes are garbage the
        moment the caller drops its copy.  Byte budgets charge on a
        namespace's 0->1 transition only."""
        field = "srefs" if ns == "session" else "prefs"
        rec = self._payloads.get(pkey)
        if rec is None:
            rec = {"payload": payload, "bytes": payload_bytes(payload),
                   "srefs": 0, "prefs": 0}
            self._payloads[pkey] = rec
        elif self.metrics is not None:
            self.metrics.inc("session_store_payload_dedup_total")
        if rec[field] == 0:
            if field == "srefs":
                self._payload_bytes += rec["bytes"]
            else:
                self._prefix_bytes += rec["bytes"]
        rec[field] += 1
        return rec["payload"]

    def _payload_unref_locked(self, pkey: Optional[str], ns: str) -> None:
        if pkey is None:
            return
        rec = self._payloads.get(pkey)
        if rec is None:
            return
        field = "srefs" if ns == "session" else "prefs"
        rec[field] -= 1
        if rec[field] == 0:
            if field == "srefs":
                self._payload_bytes -= rec["bytes"]
            else:
                self._prefix_bytes -= rec["bytes"]
        if rec["srefs"] <= 0 and rec["prefs"] <= 0:
            self._payloads.pop(pkey, None)

    def _session_payload_drop_locked(self, rec: dict) -> None:
        """Detach a session record's payload (LRU drop / overwrite):
        unref content-addressed payloads, un-charge direct ones."""
        if rec.get("pkey") is not None:
            self._payload_unref_locked(rec["pkey"], "session")
            rec["pkey"] = None
        else:
            self._payload_bytes -= rec["bytes"]
        rec["bytes"] = 0

    def _drop_locked(self, session: str, rec: dict) -> None:
        self._session_payload_drop_locked(rec)
        self._records.pop(session, None)

    def _gauges_locked(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("session_store_sessions",
                                   len(self._records))
            self.metrics.set_gauge("session_store_payload_bytes",
                                   self._payload_bytes)
            self.metrics.set_gauge("session_store_prefixes",
                                   len(self._prefixes))
            self.metrics.set_gauge("prefix_tier_resident_bytes",
                                   self._prefix_bytes)

    def _reap_locked(self, session: str) -> Optional[dict]:
        """The session's record, lease-checked: an expired record is
        dropped (counted) and reads as gone."""
        rec = self._records.get(session)
        if rec is None:
            return None
        if self._expired_locked(rec):
            self._drop_locked(session, rec)
            if self.metrics is not None:
                self.metrics.inc("session_store_lease_expired_total")
            self._gauges_locked()
            return {"__expired__": True}
        return rec

    # -- SessionStoreBackend ----------------------------------------------
    def get(self, session: str, meta: bool = False) -> StoreResult:
        with self._lock:
            rec = self._reap_locked(session)
            if rec is None:
                return StoreResult("absent")
            if "__expired__" in rec:
                return StoreResult("expired")
            entry = dict(rec["entry"])
            if meta:
                entry["payload_present"] = entry.get("payload") is not None
                entry["payload"] = None
            return StoreResult("ok", entry, rec["version"])

    def put(self, session: str, entry: dict,
            if_version: Optional[int] = None) -> StoreResult:
        with self._lock:
            if self.force_conflicts > 0:
                self.force_conflicts -= 1
                if self.metrics is not None:
                    self.metrics.inc("session_store_cas_conflicts_total")
                return StoreResult("conflict")
            rec = self._reap_locked(session)
            expired = rec is not None and "__expired__" in rec
            if expired:
                rec = None
            if if_version is not None and (
                rec is None or rec["version"] != if_version
            ):
                # a CAS put against a vanished/expired/moved-on entry:
                # the writer's view is stale, its payload must not land
                if self.metrics is not None:
                    self.metrics.inc("session_store_cas_conflicts_total")
                return StoreResult("conflict")
            version = (rec["version"] if rec is not None else 0) + 1
            entry = dict(entry)
            payload = entry.get("payload")
            pkey = payload_key(payload) if payload is not None else None
            if rec is not None:
                self._session_payload_drop_locked(rec)
            if pkey is not None:
                # content-addressed: identical bytes captured by N
                # sessions rest once (refcounted — satellite fix)
                entry["payload"] = self._payload_ref_locked(
                    pkey, payload, "session"
                )
                nbytes = 0   # charged through the payload table
            else:
                nbytes = payload_bytes(payload)
                self._payload_bytes += nbytes
            self._records[session] = {
                "entry": entry, "version": version,
                "expires": (
                    self.clock() + self.lease_s
                    if self.lease_s is not None else None
                ),
                "bytes": nbytes, "pkey": pkey,
            }
            self._records.move_to_end(session)
            # byte-bounded LRU: oldest PAYLOADS drop, streams stay —
            # those sessions degrade to cold prefill on restore, which
            # is the designed fallback, never an error.  A shared
            # payload's bytes only free when its LAST reference drops,
            # so the walk may shed several references per overage.
            if self._payload_bytes > self.max_payload_bytes:
                for other_session, other in self._records.items():
                    if self._payload_bytes <= self.max_payload_bytes:
                        break
                    if (other_session == session
                            or other["entry"].get("payload") is None):
                        continue
                    self._session_payload_drop_locked(other)
                    other["entry"]["payload"] = None
                    if self.metrics is not None:
                        self.metrics.inc(
                            "session_store_payloads_dropped_total"
                        )
            while len(self._records) > self.max_sessions:
                dropped_session, dropped = next(iter(self._records.items()))
                self._drop_locked(dropped_session, dropped)
            self._gauges_locked()
            return StoreResult("ok", version=version)

    def delete(self, session: str) -> StoreResult:
        with self._lock:
            rec = self._records.get(session)
            if rec is None:
                return StoreResult("absent")
            self._drop_locked(session, rec)
            self._gauges_locked()
            return StoreResult("ok")

    def sessions_on(self, replica_key: str) -> Optional[List[str]]:
        with self._lock:
            out = []
            for session in list(self._records):
                rec = self._reap_locked(session)
                if rec is None or "__expired__" in rec:
                    continue
                if rec["entry"].get("replica") == replica_key:
                    out.append(session)
            return out

    def _mark_locked(self, predicate) -> int:
        marked = 0
        for session in list(self._records):
            rec = self._reap_locked(session)
            if rec is None or "__expired__" in rec:
                continue
            if predicate(rec["entry"]) and not rec["entry"].get("lost"):
                rec["entry"]["lost"] = True
                # a mark IS a write: a stale capture racing it must
                # lose its CAS (the session's fate changed under it)
                rec["version"] += 1
                marked += 1
        return marked

    def mark_lost(self, replica_key: str) -> bool:
        with self._lock:
            self._mark_locked(
                lambda e: e.get("replica") == replica_key
            )
            return True

    def sync_live(self, live) -> bool:
        live = set(live)
        with self._lock:
            self._mark_locked(
                lambda e: e.get("replica") not in live
            )
            return True

    # -- prefix-tier namespace (PR 16) ------------------------------------
    def _prefix_touch_locked(self, rec: dict) -> None:
        """A hit IS heat: bump popularity, renew the TTL (immortal-
        while-hot), and advance the recency sequence."""
        rec["hits"] += 1
        self._prefix_seq += 1
        rec["last"] = self._prefix_seq
        if self.prefix_lease_s is not None:
            rec["expires"] = self.clock() + self.prefix_lease_s

    def _prefix_evict_locked(self, chain_key: str, rec: dict) -> None:
        self._prefixes.pop(chain_key, None)
        for pk in rec["page_keys"]:
            chains = self._page_index.get(pk)
            if chains is not None:
                chains.discard(chain_key)
                if not chains:
                    self._page_index.pop(pk, None)
        self._payload_unref_locked(rec["pkey"], "prefix")
        if self.metrics is not None:
            self.metrics.inc("session_store_prefix_evicted_total")

    def _reap_prefix_locked(self, chain_key: str) -> Optional[dict]:
        rec = self._prefixes.get(chain_key)
        if rec is None:
            return None
        if rec["expires"] is not None and self.clock() >= rec["expires"]:
            self._prefix_evict_locked(chain_key, rec)
            return None
        return rec

    def put_prefix(self, chain_key: str, entry: dict) -> StoreResult:
        payload = entry.get("payload") if isinstance(entry, dict) else None
        page_keys = [
            str(k) for k in (entry.get("page_keys") or [])
        ] if isinstance(entry, dict) else []
        if payload is None or not page_keys:
            return StoreResult("absent")
        with self._lock:
            rec = self._reap_prefix_locked(chain_key)
            if rec is not None:
                # double publish: a popularity signal, never a second
                # copy — the payload's refcount and bytes are unchanged
                self._prefix_touch_locked(rec)
                pl = self._payloads.get(rec["pkey"]) or {}
                self._gauges_locked()
                return StoreResult("ok", {
                    "stored": False, "hits": rec["hits"],
                    "refs": pl.get("srefs", 0) + pl.get("prefs", 0),
                }, 1)
            pkey = payload_key(payload) or f"chain:{chain_key}"
            self._payload_ref_locked(pkey, payload, "prefix")
            self._prefix_seq += 1
            self._prefixes[chain_key] = {
                "pkey": pkey, "page_keys": page_keys,
                "pages": int(entry.get("pages") or len(page_keys)),
                # the payload rides opaquely; its wire-codec tag must
                # ride with it or the GET side can't decode
                "payload_codec": entry.get("payload_codec"),
                "hits": 0, "last": self._prefix_seq,
                "expires": (
                    self.clock() + self.prefix_lease_s
                    if self.prefix_lease_s is not None else None
                ),
            }
            for pk in page_keys:
                self._page_index.setdefault(pk, set()).add(chain_key)
            # popularity-weighted LRU under the prefix byte budget: the
            # COLDEST chain goes first (fewest hits, least recent) — a
            # brand-new publish into a budget full of hotter chains
            # bounces, a hot one is never the victim
            while self._prefix_bytes > self.max_prefix_bytes and (
                self._prefixes
            ):
                victim = min(
                    self._prefixes.items(),
                    key=lambda kv: (kv[1]["hits"], kv[1]["last"]),
                )
                self._prefix_evict_locked(*victim)
            pl = self._payloads.get(pkey) or {}
            stored = chain_key in self._prefixes
            self._gauges_locked()
            return StoreResult("ok", {
                "stored": stored,
                "refs": pl.get("srefs", 0) + pl.get("prefs", 0),
            }, 1)

    def probe_prefix(self, keys: List[str]) -> StoreResult:
        with self._lock:
            for j in range(len(keys) - 1, -1, -1):
                chains = self._page_index.get(str(keys[j]))
                if not chains:
                    continue
                best: Optional[Tuple[str, dict]] = None
                for ck in list(chains):
                    rec = self._reap_prefix_locked(ck)
                    if rec is None:
                        continue
                    if best is None or (
                        (rec["hits"], rec["last"])
                        > (best[1]["hits"], best[1]["last"])
                    ):
                        best = (ck, rec)
                if best is None:
                    continue
                ck, rec = best
                self._prefix_touch_locked(rec)
                return StoreResult("ok", {
                    "chain": ck, "match_pages": j + 1,
                    "pages": rec["pages"],
                }, 1)
            return StoreResult("absent")

    def get_prefix(self, chain_key: str, meta: bool = False) -> StoreResult:
        with self._lock:
            rec = self._reap_prefix_locked(chain_key)
            if rec is None:
                return StoreResult("absent")
            entry = {"pages": rec["pages"],
                     "page_keys": list(rec["page_keys"]),
                     "hits": rec["hits"]}
            if rec.get("payload_codec") is not None:
                entry["payload_codec"] = rec["payload_codec"]
            if meta:
                entry["payload_present"] = rec["pkey"] in self._payloads
                return StoreResult("ok", entry, 1)
            pl = self._payloads.get(rec["pkey"])
            if pl is None:   # pragma: no cover - prefix refs pin payloads
                return StoreResult("absent")
            self._prefix_touch_locked(rec)
            entry["payload"] = pl["payload"]
            return StoreResult("ok", entry, 1)

    # -- chaos (soak/tests) ------------------------------------------------
    def expire_all(self) -> None:
        """Force every lease to lapse NOW (the soak's lease-expiry op)."""
        with self._lock:
            for rec in self._records.values():
                rec["expires"] = self.clock() - 1.0

    # -- views -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "sessions": len(self._records),
                "payload_bytes": self._payload_bytes,
                "with_payload": sum(
                    1 for r in self._records.values()
                    if r["entry"].get("payload") is not None
                ),
                "unique_payloads": len(self._payloads),
                "prefixes": len(self._prefixes),
                "prefix_bytes": self._prefix_bytes,
            }

    def payload_refs(self, payload) -> int:
        """Total references (sessions + prefixes) held on this
        payload's content address — the dedup tests' oracle."""
        pkey = payload_key(payload)
        with self._lock:
            rec = self._payloads.get(pkey) if pkey else None
            return (rec["srefs"] + rec["prefs"]) if rec else 0


# ---------------------------------------------------------------------------
# Payload wire codec (client-side): numpy layers <-> JSON-safe base64
# ---------------------------------------------------------------------------

def _encode_entry_for_wire(entry: dict) -> dict:
    """JSON-safe copy of an entry.  In-memory-lane payloads carry host
    numpy layers; they ride base64 on the wire (the dataplane codec) and
    the codec tag makes the GET side symmetric.  Payloads that are
    ALREADY wire-encoded (an ``HttpReplicaClient.export_sealed`` result
    — the common production case) pass through untouched: the store and
    the gateway both relay them opaquely."""
    payload = entry.get("payload")
    out = dict(entry)
    if not isinstance(payload, dict):
        out.pop("payload_codec", None)
        return out
    layers = payload.get("layers")
    if layers and not isinstance(layers[0], dict):
        from kubegpu_tpu.gateway.dataplane import encode_kv_payload

        out["payload"] = encode_kv_payload(payload)
        out["payload_codec"] = "b64"
    else:
        out["payload_codec"] = "wire"
    return out


def _decode_entry_from_wire(entry: dict) -> dict:
    out = dict(entry)
    codec = out.pop("payload_codec", None)
    if codec == "b64" and isinstance(out.get("payload"), dict):
        from kubegpu_tpu.gateway.dataplane import decode_kv_payload

        out["payload"] = decode_kv_payload(out["payload"])
    return out


# ---------------------------------------------------------------------------
# Circuit breaker + HTTP client backend
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Consecutive-failure breaker: after ``threshold`` failures in a
    row the breaker OPENS for ``cooldown_s`` — every op in the window
    fast-fails without touching the network (a dead store must cost the
    serving path microseconds, not a connect timeout per request).
    After the cooldown one trial op is let through (half-open): success
    closes the breaker, failure re-opens it for another window.  The
    clock is injectable for fake-clock tests."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._lock = threading.Lock()
        self.failures = 0
        self.open_until = 0.0
        self.trips = 0
        # half-open: exactly ONE trial op holds this token; every other
        # caller keeps fast-failing until the trial reports back — N
        # dispatcher threads must not all stall an op deadline against
        # a hung store at every cooldown expiry
        self._trial_inflight = False

    def allow(self) -> bool:
        """May this op touch the network?  Claims the half-open trial
        token when the cooldown just expired — the caller MUST report
        back via ``success()``/``failure()`` after a True."""
        with self._lock:
            if self.open_until == 0.0:
                return True
            if self.clock() < self.open_until:
                return False
            if self._trial_inflight:
                return False
            self._trial_inflight = True
            return True

    @property
    def open(self) -> bool:
        with self._lock:
            return (self.open_until != 0.0
                    and self.clock() < self.open_until)

    def success(self) -> None:
        with self._lock:
            self.failures = 0
            self.open_until = 0.0
            self._trial_inflight = False

    def failure(self) -> None:
        with self._lock:
            self._trial_inflight = False
            self.failures += 1
            if self.failures >= self.threshold:
                self.open_until = self.clock() + self.cooldown_s
                self.trips += 1


class _Unreachable(Exception):
    pass


class HttpStoreClient(SessionStoreBackend):
    """``SessionStoreBackend`` over the store's HTTP protocol.

    Failure discipline (the whole point of this class): every op has a
    per-op DEADLINE (``timeout_s`` socket timeout), transport errors
    retry a bounded number of times with exponential backoff + jitter
    (the registry probe-backoff shape: ``base * 2^k``, capped, jitter
    in [0.5, 1.5)x, injectable clock/sleep/rng), and a circuit breaker
    turns a dead store into one fast-fail per op.  Nothing here raises
    to the caller: ops resolve ``StoreResult("unreachable")`` and the
    ``SessionKVStore`` above degrades the session to cold prefill."""

    def __init__(self, url: str, timeout_s: float = 1.0,
                 retries: int = 1,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 0.5,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None,
                 metrics: Optional[Metrics] = None) -> None:
        addr = url
        for prefix in ("http://", "https://"):
            if addr.startswith(prefix):
                addr = addr[len(prefix):]
        addr = addr.rstrip("/")
        host, _, port = addr.rpartition(":")
        if not host:
            raise ValueError(
                f"session-store url {url!r} must be host:port (or "
                "http://host:port)"
            )
        self.host, self.port = host, int(port)
        self.timeout_s = timeout_s
        self.retries = retries
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random(0)
        self.metrics = metrics
        self.breaker = CircuitBreaker(
            breaker_threshold, breaker_cooldown_s, clock=clock
        )
        # small keep-alive pool (the store answers HTTP/1.1 with
        # Content-Length): a per-dispatch metadata GET must not pay a
        # TCP setup each time.  Transport errors flush the WHOLE pool —
        # a restarted store leaves every pooled socket stale.
        self._conn_lock = threading.Lock()
        self._conns: List[http.client.HTTPConnection] = []

    # -- transport ---------------------------------------------------------
    def _checkout(self) -> http.client.HTTPConnection:
        with self._conn_lock:
            if self._conns:
                return self._conns.pop()
        return http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout_s
        )

    def _checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._conn_lock:
            if len(self._conns) < 8:
                self._conns.append(conn)
                return
        conn.close()

    def _flush_pool(self) -> None:
        with self._conn_lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            conn.close()

    def _do(self, method: str, path: str,
            body: Optional[dict] = None) -> Tuple[int, dict]:
        """One HTTP round-trip under the per-op deadline, on a pooled
        keep-alive connection.  Raises ``OSError`` flavors on transport
        failure; monkeypatch target for the fake-clock breaker/backoff
        units."""
        conn = self._checkout()
        try:
            conn.request(
                method, path,
                json.dumps(body) if body is not None else None,
                {"Content-Type": "application/json"} if body is not None
                else {},
            )
            resp = conn.getresponse()
            raw = resp.read()
        except Exception:
            conn.close()
            self._flush_pool()
            raise
        try:
            payload = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            payload = {}
        self._checkin(conn)
        return resp.status, payload

    def _call(self, method: str, path: str,
              body: Optional[dict] = None) -> Tuple[int, dict]:
        """Breaker + bounded-retry wrapper around ``_do``.  Raises
        ``_Unreachable`` when the op could not be served."""
        if not self.breaker.allow():
            if self.metrics is not None:
                self.metrics.inc("gateway_session_store_fastfail_total")
            raise _Unreachable("session store breaker open")
        delay = self.backoff_base_s
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            try:
                status, payload = self._do(method, path, body)
            except Exception as e:  # noqa: BLE001 - every transport
                # failure mode must release the breaker's half-open
                # trial token (a leaked token = permanent fast-fail)
                # and resolve as "unreachable", never propagate
                self.breaker.failure()
                last = e
                if attempt < self.retries and self.breaker.allow():
                    if self.metrics is not None:
                        self.metrics.inc(
                            "gateway_session_store_retries_total"
                        )
                    self._sleep(delay * (0.5 + self._rng.random()))
                    delay = min(delay * 2, self.backoff_cap_s)
                    continue
                raise _Unreachable(str(e)) from e
            self.breaker.success()
            return status, payload
        raise _Unreachable(str(last))   # pragma: no cover - loop exits above

    # -- SessionStoreBackend ----------------------------------------------
    def get(self, session: str, meta: bool = False) -> StoreResult:
        try:
            status, payload = self._call(
                "GET",
                f"/v1/session/{quote(session, safe='')}"
                + ("?meta=1" if meta else ""),
            )
        except _Unreachable:
            return StoreResult("unreachable")
        if status == 200:
            return StoreResult(
                "ok",
                _decode_entry_from_wire(payload.get("entry") or {}),
                int(payload.get("version", 0)),
            )
        if status == 404 and payload.get("reason") == "lease_expired":
            return StoreResult("expired")
        if status == 404:
            return StoreResult("absent")
        return StoreResult("unreachable")

    def put(self, session: str, entry: dict,
            if_version: Optional[int] = None) -> StoreResult:
        try:
            status, payload = self._call(
                "PUT", f"/v1/session/{quote(session, safe='')}",
                {"entry": _encode_entry_for_wire(entry),
                 "if_version": if_version},
            )
        except _Unreachable:
            return StoreResult("unreachable")
        if status == 200:
            return StoreResult("ok", version=int(payload.get("version", 0)))
        if status == 409:
            return StoreResult("conflict")
        return StoreResult("unreachable")

    def delete(self, session: str) -> StoreResult:
        try:
            status, _ = self._call(
                "DELETE", f"/v1/session/{quote(session, safe='')}"
            )
        except _Unreachable:
            return StoreResult("unreachable")
        return StoreResult("ok" if status == 200 else "absent")

    def sessions_on(self, replica_key: str) -> Optional[List[str]]:
        try:
            status, payload = self._call(
                "GET", f"/v1/sessions?replica={quote(replica_key, safe='')}"
            )
        except _Unreachable:
            return None
        if status != 200:
            return None
        return [str(s) for s in payload.get("sessions", [])]

    def mark_lost(self, replica_key: str) -> bool:
        try:
            status, _ = self._call(
                "POST", "/v1/mark", {"replica": replica_key}
            )
        except _Unreachable:
            return False
        return status == 200

    def sync_live(self, live) -> bool:
        try:
            status, _ = self._call(
                "POST", "/v1/mark", {"live": sorted(live)}
            )
        except _Unreachable:
            return False
        return status == 200

    # -- prefix-tier namespace --------------------------------------------
    def put_prefix(self, chain_key: str, entry: dict) -> StoreResult:
        try:
            status, payload = self._call(
                "PUT", f"/v1/prefix/{quote(chain_key, safe='')}",
                {"entry": _encode_entry_for_wire(entry)},
            )
        except _Unreachable:
            return StoreResult("unreachable")
        if status == 200:
            return StoreResult("ok", dict(payload), 1)
        return StoreResult("unreachable" if status >= 500 else "absent")

    def probe_prefix(self, keys: List[str]) -> StoreResult:
        try:
            status, payload = self._call(
                "POST", "/v1/prefix/probe",
                {"keys": [str(k) for k in keys]},
            )
        except _Unreachable:
            return StoreResult("unreachable")
        if status == 200:
            return StoreResult("ok", dict(payload), 1)
        if status == 404:
            return StoreResult("absent")
        return StoreResult("unreachable")

    def get_prefix(self, chain_key: str, meta: bool = False) -> StoreResult:
        try:
            status, payload = self._call(
                "GET",
                f"/v1/prefix/{quote(chain_key, safe='')}"
                + ("?meta=1" if meta else ""),
            )
        except _Unreachable:
            return StoreResult("unreachable")
        if status == 200:
            return StoreResult(
                "ok",
                _decode_entry_from_wire(payload.get("entry") or {}),
                1,
            )
        if status == 404:
            return StoreResult("absent")
        return StoreResult("unreachable")

    def healthy(self) -> bool:
        try:
            status, _ = self._call("GET", "/healthz")
        except _Unreachable:
            return False
        return status == 200

    def close(self) -> None:
        self._flush_pool()


# ---------------------------------------------------------------------------
# SessionKVStore: the gateway's insurance ledger over a pluggable backend
# ---------------------------------------------------------------------------

class SessionKVStore:
    """The gateway's failover insurance for session KV: per session, the
    replica that last served it, the stream it ended on (prompt +
    generated tokens — the chain identity), and the last SEALED EXPORT
    captured from that replica (``client.export_sealed``).  When the
    replica later dies — or is drained, or cold-restarts under the same
    name — and the session dispatches again, the dispatcher imports the
    stored payload into the target BEFORE the attempt opens, so turn 2
    hits warm pages instead of cold-restarting prefill.

    Storage is a ``SessionStoreBackend``: the default in-process backend
    is one dict (a single gateway, or an in-process tier sharing one
    instance); ``HttpStoreClient`` points every gateway POD at one
    external ``StoreServer`` so the insurance survives gateway death.

    The robustness contract is graceful degradation END TO END: store
    unreachable, a CAS conflict, a lapsed lease — every store failure
    resolves as a cold prefill with
    ``gateway_session_store_degraded_total{reason}`` incremented (and a
    ``degraded_log`` entry the soak audits), NEVER a request error.

    Captures write through asynchronously (``capture_async``): a bounded
    queue drained by one background thread, drop-OLDEST on overflow —
    capture is insurance, and must never block the result path or grow
    without bound when the store browns out."""

    def __init__(self, max_sessions: int = 4096,
                 max_payload_bytes: int = 256 << 20,
                 backend: Optional[SessionStoreBackend] = None,
                 metrics: Optional[Metrics] = None,
                 capture_queue: int = 64) -> None:
        self.max_sessions = max_sessions
        self.max_payload_bytes = max_payload_bytes
        self.backend = backend if backend is not None else (
            InProcessStoreBackend(
                max_sessions=max_sessions,
                max_payload_bytes=max_payload_bytes,
            )
        )
        self.metrics = metrics
        self.capture_queue = capture_queue
        # per-GATEWAY session→home hint cache: the healthy-home no-op is
        # the dispatch hot path's common case, and against an external
        # store it costs a metadata GET per dispatch.  A hint records
        # "this session's entry is home=replica, not lost" so repeat
        # dispatches to the SAME replica skip the store round-trip
        # entirely.  Invalidation is aggressive — any ring movement
        # (mark_lost/sync_live), any degrade, any restore, any read that
        # contradicts the hint drops it — because a stale hint costs at
        # most one skipped mispin-restore (a perf blip), never
        # correctness: the replica's own caches and the cold-prefill
        # fallback stay sound.  In the multi-process deployment each
        # gateway pod holds its own SessionKVStore, so this cache is
        # per-gateway by construction.
        self._hints: Dict[str, str] = {}
        # every degrade event, in order: (session, reason) — the soak's
        # audit trail ("every degraded session completed cold, counted")
        self.degraded_log: List[Tuple[str, str]] = []
        self._cond = threading.Condition()
        self._queue: deque = deque()          # (client, session)
        self._inflight_captures = 0
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    # -- degradation accounting -------------------------------------------
    def _degrade(self, session: str, reason: str) -> None:
        with self._cond:
            self.degraded_log.append((session, reason))
            self._hints.pop(session, None)
        if self.metrics is not None:
            self.metrics.inc(
                "gateway_session_store_degraded_total", reason=reason
            )

    # -- the ledger --------------------------------------------------------
    def record(self, session: str, replica_key: str, stream) -> None:
        """A sessionful turn completed: remember where and on what
        stream.  A new turn supersedes the old entry (the chain grew) —
        an UNCONDITIONAL put, which is exactly what makes stale captures
        lose their CAS."""
        entry = {
            "replica": replica_key,
            "stream": [int(t) for t in stream],
            "payload": None,
            "lost": False,
        }
        res = self.backend.put(session, entry, if_version=None)
        if res.status == "unreachable":
            self._degrade(session, "unreachable")
            return
        # the turn just completed HERE: the healthy home is known
        # without asking the store again
        with self._cond:
            self._hints[session] = replica_key
            if len(self._hints) > self.max_sessions:
                self._hints.clear()

    def capture(self, client, session: str) -> bool:
        """Export the session's sealed chain from its home replica and
        write it through — the insurance premium, paid while the
        replica is alive.  Best-effort: False leaves the entry
        payload-less (a later death then degrades to cold prefill).
        The put is CAS-guarded against the version the stream was READ
        at, so a capture racing a newer turn (possibly on a sibling
        gateway) can never interleave a stale payload over a newer
        seal.  A metadata read suffices: the capture needs the stream
        and the version, and OVERWRITES the payload anyway."""
        res = self.backend.get(session, meta=True)
        if res.status == "unreachable":
            self._degrade(session, "unreachable")
            return False
        if res.status == "expired":
            self._degrade(session, "lease_expired")
            return False
        if res.status != "ok":
            return False
        entry, version = res.entry, res.version
        try:
            payload = client.export_sealed(
                entry["replica"], list(entry["stream"])
            )
        except Exception:  # noqa: BLE001 - capture is best-effort
            log.exception("sealed-chain export failed")
            return False
        if payload is None:
            return False
        new = dict(entry)
        new.pop("payload_present", None)   # meta-read artifact
        new["payload"] = payload
        put = self.backend.put(session, new, if_version=version)
        if put.status == "conflict":
            self._degrade(session, "cas_conflict")
            return False
        if put.status == "unreachable":
            self._degrade(session, "unreachable")
            return False
        return put.status == "ok"

    # -- async write-through ----------------------------------------------
    def capture_async(self, client, session: str) -> None:
        """Queue a capture off the result path.  Bounded, drop-OLDEST
        (the newest capture is the one a restore most likely needs),
        deduped by session (a burst of turns folds into one export)."""
        with self._cond:
            if self._closed:
                return
            for i, (_, queued_session) in enumerate(self._queue):
                if queued_session == session:
                    del self._queue[i]
                    break
            self._queue.append((client, session))
            dropped = 0
            while len(self._queue) > self.capture_queue:
                self._queue.popleft()
                dropped += 1
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._capture_loop, name="session-kv-capture",
                    daemon=True,
                )
                self._worker.start()
            self._cond.notify()
        if dropped and self.metrics is not None:
            self.metrics.inc(
                "gateway_session_store_capture_drops_total", dropped
            )

    def _capture_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.1)
                if self._closed and not self._queue:
                    return
                client, session = self._queue.popleft()
                self._inflight_captures += 1
            try:
                self.capture(client, session)
            except Exception:  # noqa: BLE001 - insurance must never raise
                log.exception("async capture failed for %s", session)
            finally:
                with self._cond:
                    self._inflight_captures -= 1
                    self._cond.notify_all()

    def flush_captures(self, timeout: float = 10.0) -> bool:
        """Wait for every queued capture to land (tests, drains)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._inflight_captures:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        close_backend = getattr(self.backend, "close", None)
        if close_backend is not None:
            close_backend()

    # -- lifecycle marks ---------------------------------------------------
    def sessions_on(self, replica_key: str) -> List[str]:
        return self.backend.sessions_on(replica_key) or []

    def mark_lost(self, replica_key: str) -> None:
        """The replica is going (drain) or gone (death): its sessions'
        next dispatch may restore elsewhere — or back into the SAME pod
        name once it cold-restarts.  Ring movement invalidates the
        whole hint cache: a hint that survived a membership change
        could mask the very restore the movement calls for."""
        with self._cond:
            self._hints.clear()
        self.backend.mark_lost(replica_key)

    def sync_live(self, live) -> None:
        """Registry subscription: sessions homed on replicas that left
        the live set become restorable.  Clears the hint cache (ring
        movement — see ``mark_lost``)."""
        with self._cond:
            self._hints.clear()
        self.backend.sync_live(live)

    # -- restore (the read-through, on the dispatch path) ------------------
    def restore_for(self, request, target_key: str, client,
                    mispin_restore: bool = True) -> bool:
        """Called at dispatch time with the routed target: if this
        request's session is dispatching AWAY from its recorded home —
        or back to a home that was LOST (death, drain, cold restart
        under the same pod name) — and a sealed export was captured,
        import it into the target (idempotent — the import dedups
        against pages already cached there) and re-home the entry.
        ``mispin_restore=False`` is for load-balancing routers with NO
        session affinity: only a LOST home restores there.  True only
        when a payload actually landed.  Every store failure on this
        path degrades to cold prefill, counted — never an error.

        Two-phase read: this runs on the DISPATCH hot path for every
        sessionful request, and the common case is the healthy-home
        no-op — so the decision is made on a METADATA read (no payload
        bytes moved), and only an actual restore pays the full fetch.
        Hotter still, the per-gateway hint cache skips even that GET
        when the routed target IS the session's last known healthy home
        (hints drop on any ring movement, degrade, or restore — see
        ``_hints``), so steady healthy-home traffic costs the store
        nothing per dispatch."""
        session = getattr(request, "session", None)
        if not session:
            return False
        with self._cond:
            if self._hints.get(session) == target_key:
                return False    # healthy home, hinted: skip the store GET
        res = self.backend.get(session, meta=True)
        if res.status == "unreachable":
            self._degrade(session, "unreachable")
            return False
        if res.status == "expired":
            self._degrade(session, "lease_expired")
            return False
        if res.status != "ok":
            return False
        lost = bool(res.entry.get("lost"))
        if res.entry.get("replica") == target_key and not lost:
            # healthy home: the replica has its own cache.  Remember it
            # so the next dispatch here skips the metadata GET entirely.
            with self._cond:
                self._hints[session] = target_key
                if len(self._hints) > self.max_sessions:
                    self._hints.clear()
            return False
        with self._cond:
            self._hints.pop(session, None)
        if not lost and not mispin_restore:
            return False
        if not res.entry.get("payload_present"):
            return False    # nothing sealed: cold prefill, by design
        full = self.backend.get(session)
        if full.status == "unreachable":
            self._degrade(session, "unreachable")
            return False
        if full.status == "expired":
            self._degrade(session, "lease_expired")
            return False
        if full.status != "ok":
            return False
        e, version = full.entry, full.version
        payload = e.get("payload")
        if payload is None:
            return False    # evicted between the two reads: cold
        try:
            if not client.import_sealed(target_key, payload):
                return False
        except Exception:  # noqa: BLE001 - restore is best-effort
            log.exception("sealed-chain import failed")
            return False
        new = dict(e)
        new["replica"] = target_key
        new["lost"] = False
        # re-home CAS-guarded: losing this race just means a newer turn
        # (or a sibling's restore) already owns the entry — the import
        # itself landed either way, so no degrade
        self.backend.put(session, new, if_version=version)
        return True

    # -- test/diagnostic views --------------------------------------------
    def entry(self, session: str) -> Optional[dict]:
        res = self.backend.get(session)
        return dict(res.entry) if res.status == "ok" else None

    def set_payload(self, session: str, payload) -> bool:
        """Inject an insurance payload directly (tests)."""
        res = self.backend.get(session)
        if res.status != "ok":
            return False
        e = dict(res.entry)
        e["payload"] = payload
        return self.backend.put(
            session, e, if_version=res.version
        ).status == "ok"


# ---------------------------------------------------------------------------
# The standalone HTTP store
# ---------------------------------------------------------------------------

def make_store_handler(backend: InProcessStoreBackend, metrics: Metrics,
                       chaos: bool = False):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # keep-alive clients (HttpStoreClient pools connections) would
        # otherwise pay a ~40 ms Nagle/delayed-ACK stall per op on the
        # server's buffered response writes — measured 44 ms/op reused
        # vs 0.2 ms with NODELAY
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):
            log.debug("session store: " + fmt, *args)

        def _read_json(self) -> Optional[dict]:
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                return json.loads(raw) if raw else {}
            except (ValueError, json.JSONDecodeError):
                return None

        def _send(self, code: int, payload,
                  content_type="application/json") -> None:
            body = (
                json.dumps(payload).encode()
                if content_type == "application/json"
                else payload.encode()
            )
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _session_of(self, path: str) -> Optional[str]:
            prefix = "/v1/session/"
            if not path.startswith(prefix) or len(path) <= len(prefix):
                return None
            return unquote(path[len(prefix):])

        def _prefix_of(self, path: str) -> Optional[str]:
            prefix = "/v1/prefix/"
            if not path.startswith(prefix) or len(path) <= len(prefix):
                return None
            return unquote(path[len(prefix):])

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                self._send(200, "ok", content_type="text/plain")
                return
            if path == "/metrics":
                self._send(200, metrics.render(), content_type="text/plain")
                return
            if path == "/v1/sessions":
                metrics.inc("session_store_requests_total", verb="list")
                replica = (parse_qs(query).get("replica") or [""])[0]
                sessions = backend.sessions_on(replica)
                self._send(200, {"sessions": sessions or []})
                return
            chain = self._prefix_of(path)
            if chain is not None:
                metrics.inc("session_store_requests_total",
                            verb="prefix_get")
                meta = (parse_qs(query).get("meta") or ["0"])[0] == "1"
                res = backend.get_prefix(chain, meta=meta)
                if res.status == "ok":
                    self._send(200, {"entry": res.entry})
                else:
                    self._send(404, {"error": f"no prefix {chain!r}",
                                     "reason": "absent"})
                return
            session = self._session_of(path)
            if session is None:
                self._send(404, {"error": f"no route {path}"})
                return
            metrics.inc("session_store_requests_total", verb="get")
            # ?meta=1: metadata only — the dispatch hot path's "is a
            # restore even needed" check must not download the payload
            meta = (parse_qs(query).get("meta") or ["0"])[0] == "1"
            res = backend.get(session, meta=meta)
            if res.status == "ok":
                self._send(200, {"entry": res.entry,
                                 "version": res.version})
            elif res.status == "expired":
                self._send(404, {"error": f"session {session!r} lease "
                                 "expired", "reason": "lease_expired"})
            else:
                self._send(404, {"error": f"no session {session!r}",
                                 "reason": "absent"})

        def do_PUT(self):
            chain = self._prefix_of(self.path.partition("?")[0])
            if chain is not None:
                metrics.inc("session_store_requests_total",
                            verb="prefix_put")
                body = self._read_json()
                if body is None or not isinstance(body.get("entry"), dict):
                    self._send(400, {"error": "entry required"})
                    return
                # the payload rides opaquely (wire-encoded by the
                # client) — the store never decodes KV bytes
                res = backend.put_prefix(chain, body["entry"])
                if res.status == "ok":
                    self._send(200, dict(res.entry or {}))
                else:
                    self._send(400, {"error": "unusable prefix entry"})
                return
            session = self._session_of(self.path.partition("?")[0])
            if session is None:
                self._send(404, {"error": f"no route {self.path}"})
                return
            metrics.inc("session_store_requests_total", verb="put")
            body = self._read_json()
            if body is None or not isinstance(body.get("entry"), dict):
                self._send(400, {"error": "entry required"})
                return
            if_version = body.get("if_version")
            if if_version is not None:
                try:
                    if_version = int(if_version)
                except (TypeError, ValueError):
                    self._send(400, {"error": "if_version must be an int"})
                    return
            res = backend.put(session, body["entry"], if_version=if_version)
            if res.status == "ok":
                self._send(200, {"version": res.version})
            elif res.status == "conflict":
                self._send(409, {"error": "version conflict",
                                 "reason": "cas_conflict"})
            else:   # pragma: no cover - in-process backend cannot fail
                self._send(500, {"error": res.status})

        def do_DELETE(self):
            session = self._session_of(self.path.partition("?")[0])
            if session is None:
                self._send(404, {"error": f"no route {self.path}"})
                return
            metrics.inc("session_store_requests_total", verb="delete")
            res = backend.delete(session)
            self._send(200, {"deleted": res.status == "ok"})

        def do_POST(self):
            path = self.path.partition("?")[0]
            if path == "/v1/prefix/probe":
                metrics.inc("session_store_requests_total",
                            verb="prefix_probe")
                body = self._read_json()
                if body is None or not isinstance(body.get("keys"), list):
                    self._send(400, {"error": "keys required"})
                    return
                res = backend.probe_prefix(
                    [str(k) for k in body["keys"]]
                )
                if res.status == "ok":
                    self._send(200, dict(res.entry or {}))
                else:
                    self._send(404, {"reason": "absent"})
                return
            if path == "/v1/mark":
                metrics.inc("session_store_requests_total", verb="mark")
                body = self._read_json()
                if body is None:
                    self._send(400, {"error": "malformed JSON body"})
                    return
                if body.get("replica"):
                    backend.mark_lost(str(body["replica"]))
                elif body.get("live") is not None:
                    backend.sync_live([str(k) for k in body["live"]])
                else:
                    self._send(400, {"error": "replica or live required"})
                    return
                self._send(200, {"marked": True})
                return
            if path == "/v1/chaos" and chaos:
                body = self._read_json() or {}
                if body.get("conflicts"):
                    backend.force_conflicts += int(body["conflicts"])
                if body.get("expire_all"):
                    backend.expire_all()
                self._send(200, {"ok": True})
                return
            self._send(404, {"error": f"no route {path}"})

    return Handler


class StoreServer:
    """The standalone session-KV store process: one
    ``InProcessStoreBackend`` behind a threaded HTTP server.  ``listen``
    port 0 picks an ephemeral port (tests); ``stop()`` closes the
    listener — entries are in-memory (the store is INSURANCE: losing it
    degrades every session to cold prefill, which the gateway handles
    by design, so a store restart is an availability blip, not a
    correctness event)."""

    def __init__(self, listen: Tuple[str, int] = ("127.0.0.1", 0),
                 max_sessions: int = 4096,
                 max_payload_bytes: int = 256 << 20,
                 lease_s: Optional[float] = 3600.0,
                 max_prefix_bytes: int = 256 << 20,
                 prefix_lease_s: Optional[float] = None,
                 metrics: Optional[Metrics] = None,
                 backend: Optional[InProcessStoreBackend] = None,
                 chaos: bool = False) -> None:
        from http.server import ThreadingHTTPServer

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

            def handle_error(self, request, client_address):
                log.debug("store connection error from %s", client_address,
                          exc_info=True)

        self.metrics = metrics if metrics is not None else Metrics()
        self.backend = backend if backend is not None else (
            InProcessStoreBackend(
                max_sessions=max_sessions,
                max_payload_bytes=max_payload_bytes,
                lease_s=lease_s,
                max_prefix_bytes=max_prefix_bytes,
                prefix_lease_s=prefix_lease_s,
                metrics=self.metrics,
            )
        )
        if self.backend.metrics is None:
            self.backend.metrics = self.metrics
        self.httpd = _Server(
            listen, make_store_handler(self.backend, self.metrics,
                                       chaos=chaos)
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    @property
    def port(self) -> int:
        return self.address[1]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "StoreServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# CLI: python -m kubegpu_tpu.gateway.sessionstore
# ---------------------------------------------------------------------------

def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="Standalone session-KV store for the gateway tier "
        "(deploy/session-store.yaml): versioned CAS puts, per-session "
        "leases, byte-bounded payload LRU.  Gateways point at it with "
        "--session-store http://host:port."
    )
    ap.add_argument("--listen", default="127.0.0.1:8650")
    ap.add_argument("--max-sessions", type=int, default=4096)
    ap.add_argument(
        "--max-payload-bytes", type=int, default=256 << 20,
        help="total retained KV payload bytes across sessions; over "
        "budget the OLDEST payloads drop (their stream records stay — "
        "those sessions degrade to cold prefill on restore)",
    )
    ap.add_argument(
        "--lease", type=float, default=3600.0,
        help="per-session lease seconds (every put renews; an expired "
        "session reads as gone and its next turn cold-prefills).  "
        "<= 0 disables leasing",
    )
    ap.add_argument(
        "--max-prefix-bytes", type=int, default=256 << 20,
        help="retained bytes for the shared-prefix tier (a SEPARATE "
        "budget from session payloads); over budget the COLDEST chain "
        "evicts first — popularity-weighted LRU, a hot system prompt "
        "is never the victim",
    )
    ap.add_argument(
        "--prefix-lease", type=float, default=0.0,
        help="prefix-chain TTL seconds, renewed on every probe hit / "
        "fetch / re-publish (immortal-while-hot).  <= 0 disables "
        "expiry (the default — eviction is then purely byte-budgeted)",
    )
    ap.add_argument(
        "--chaos", action="store_true",
        help="enable POST /v1/chaos (forced CAS conflicts, lease "
        "expiry) — soak/test harnesses only, never production",
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO
    )
    host, _, port = args.listen.rpartition(":")
    server = StoreServer(
        listen=(host or "127.0.0.1", int(port)),
        max_sessions=args.max_sessions,
        max_payload_bytes=args.max_payload_bytes,
        lease_s=args.lease if args.lease > 0 else None,
        max_prefix_bytes=args.max_prefix_bytes,
        prefix_lease_s=args.prefix_lease if args.prefix_lease > 0 else None,
        chaos=args.chaos,
    )
    server.start()
    print(f"SESSION_STORE_SERVING port={server.port} "
          f"lease={args.lease if args.lease > 0 else 0}", flush=True)
    import signal

    shutdown = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: shutdown.set())
    try:
        # timeout loop so the main thread keeps servicing signals (a
        # bare wait() can park in an uninterruptible acquire)
        while not shutdown.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass
    server.stop()


if __name__ == "__main__":
    main()
