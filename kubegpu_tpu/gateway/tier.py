"""GatewayTier: N gateways, zero single points of failure.

One ``Gateway`` process was both the bottleneck and the SPOF fronting
the whole serving stack.  The tier removes both without adding a
coordination service, leaning on three facts:

- **shared registry view**: every gateway reads the same pod/node
  annotations (and the same data-plane probes), so membership agreement
  is the cluster's, not the tier's;
- **consistent-hash routing** (``ConsistentHashRouter``): session →
  replica is a pure function of (session_id, routable set), so any
  gateway routes any session identically — a client can hit any
  gateway and land on the session's KV;
- **shared ``SessionKVStore``**: sealed-KV insurance one gateway
  captured survives that gateway's death, so a sibling taking over a
  session restores its pages instead of cold-prefilling (in a real
  multi-process deployment this store is the external piece — a small
  KV service; in-process the tier shares one instance, which models
  exactly the same contract).

Failure contract (the client side of the tier): a request is homed on a
gateway by consistent hashing over the gateway ids (sessionless traffic
round-robins) — the stand-in for a load balancer every client agrees
with.  If the home gateway dies mid-request, the submission resolves
with an explicit "gateway died" error and ``submit_and_wait`` retries
the SAME request_id on the next gateway clockwise: the replica-side
duplicate-id eviction guarantees at most one live stream per request
tier-wide (no double-serve), the session restore re-warms the KV, and
for streaming callers the resume watermark fast-forwards the sibling's
stream past the tokens already delivered — the caller's stream is each
token exactly once, across a gateway crash.

Degradation rules when a sibling dies: nothing re-routes eagerly — the
dead gateway's in-flight work aborts (attempts cancel wire-level, so no
replica decodes for a corpse), its queued/pending requests fail fast
with the retryable error, and the survivors simply absorb the keyspace
(the gateway ring moves only the dead gateway's share).  Replica
routing does NOT change: sessions stay on their replicas because the
replica ring never saw the gateway die.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, FrozenSet, List, Optional

from kubegpu_tpu.gateway.client import ReplicaClient
from kubegpu_tpu.gateway.core import (
    Gateway,
    GatewayRequest,
    GatewayResult,
    PendingRequest,
)
from kubegpu_tpu.gateway.failover import FailoverPolicy, SessionKVStore
from kubegpu_tpu.gateway.hashring import ConsistentHashRing
from kubegpu_tpu.gateway.registry import ReplicaRegistry
from kubegpu_tpu.gateway.router import ConsistentHashRouter, Router
from kubegpu_tpu.utils.metrics import Metrics, default_metrics

# terminal errors that mean "this GATEWAY is gone", not "this request
# failed": the tier client's retry-on-a-sibling triggers.  "cancelled:
# caller disconnected" joins them only when the gateway is dead — a
# kill() aborts in-flight requests through the same abort event a
# vanished caller would use, and the two records race.
_DEATH_ERRORS = ("gateway died", "gateway shutting down")


def is_gateway_death(result: Optional[GatewayResult],
                     gateway: Optional[Gateway] = None) -> bool:
    """Should the tier client retry this result on a sibling?"""
    if result is None or result.status != "error":
        return False
    if any(err in result.error for err in _DEATH_ERRORS):
        return True
    return gateway is not None and not gateway.alive


class GatewayTier:
    """N ``Gateway`` instances over one registry, one data-plane client
    and one session store.  In production each instance is its own pod
    behind a load balancer; in-process the tier is the test/bench/chaos
    surface for everything the multi-gateway contract promises."""

    def __init__(
        self,
        registry: ReplicaRegistry,
        client: ReplicaClient,
        n_gateways: int = 2,
        gateway_ids: Optional[List[str]] = None,
        policy: Optional[FailoverPolicy] = None,
        metrics: Optional[Metrics] = None,
        dispatchers: int = 4,
        queue_factory: Optional[Callable[[], object]] = None,
        router_factory: Optional[Callable[[], Router]] = None,
        tracer_factory: Optional[Callable[[str], object]] = None,
        session_store: Optional[SessionKVStore] = None,
        prefix_tier=None,
        trace: bool = True,
    ) -> None:
        if gateway_ids is None:
            gateway_ids = [f"gw{i}" for i in range(n_gateways)]
        if len(gateway_ids) < 1:
            raise ValueError("a tier needs at least one gateway")
        self.registry = registry
        self.client = client
        self.metrics = metrics or default_metrics
        self.policy = policy
        self.dispatchers = dispatchers
        self.queue_factory = queue_factory
        # every gateway gets its OWN router instance of the SAME policy:
        # consistent hashing makes the instances agree without sharing
        # state — which is the whole point
        self.router_factory = router_factory or (
            lambda: ConsistentHashRouter()
        )
        self.tracer_factory = tracer_factory
        self.trace = trace
        # ONE store across the tier: insurance captured by any gateway
        # restores through any sibling.  In-process that is one shared
        # instance; a real multi-pod tier passes a store backed by the
        # external StoreServer (sessionstore.HttpStoreClient) instead.
        self.session_store = session_store or SessionKVStore(
            metrics=self.metrics
        )
        # ONE prefix tier across the tier, same shape: a chain published
        # by any gateway imports through any sibling (and the advisory
        # warmth map every PrefixLocalityRouter scores by is shared).
        self.prefix_tier = prefix_tier
        self._lock = threading.Lock()
        self._rr = 0
        self._ring = ConsistentHashRing(gateway_ids)
        self.gateway_ids = list(gateway_ids)
        self.gateways: Dict[str, Gateway] = {
            gid: self._build(gid) for gid in gateway_ids
        }
        self._started = False
        # tier-wide brownout state: applied to every alive gateway, and
        # re-applied to revived replacements (a fresh pod joining a
        # browned-out tier must not hedge while its siblings shed)
        self._brownout: int = 0
        self._shed_tenants: frozenset = frozenset()

    def _build(self, gid: str) -> Gateway:
        kwargs: dict = {}
        if self.queue_factory is not None:
            kwargs["queue"] = self.queue_factory()
        if self.tracer_factory is not None:
            kwargs["tracer"] = self.tracer_factory(gid)
        return Gateway(
            self.registry, self.client,
            router=self.router_factory(),
            policy=self.policy,
            metrics=self.metrics,
            dispatchers=self.dispatchers,
            trace=self.trace,
            session_store=self.session_store,
            prefix_tier=self.prefix_tier,
            gateway_id=gid,
            **kwargs,
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "GatewayTier":
        for gw in self.gateways.values():
            if gw.alive and not gw._threads:
                gw.start()
        self._started = True
        self._publish_gauge()
        return self

    def stop(self) -> None:
        for gw in self.gateways.values():
            if gw.alive:
                gw.stop()

    def alive_ids(self) -> List[str]:
        return sorted(
            gid for gid, gw in self.gateways.items() if gw.alive
        )

    def _publish_gauge(self) -> None:
        self.metrics.set_gauge(
            "gateway_tier_gateways", len(self.alive_ids())
        )

    def kill(self, gid: str) -> None:
        """Chaos surface: one gateway dies abruptly (its in-flight work
        aborts + cancels wire-level; its pendings resolve with the
        retryable death error).  The survivors absorb its keyspace."""
        gw = self.gateways[gid]
        if gw.alive:
            gw.kill()
            self.metrics.inc("gateway_tier_deaths_total")
        self._publish_gauge()

    def revive(self, gid: str) -> Gateway:
        """A replacement gateway process under the same id (fresh
        queues, fresh dispatcher pool, clean result table — nothing of
        the corpse survives except what was DESIGNED to be shared: the
        registry view and the session store)."""
        old = self.gateways.get(gid)
        if old is not None and old.alive:
            return old
        gw = self._build(gid)
        self.gateways[gid] = gw
        if self._started:
            gw.start()
        if self._brownout:
            gw.set_brownout(self._brownout,
                            shed_tenants=self._shed_tenants)
        self._publish_gauge()
        return gw

    # -- overload brownout (controller surface, fanned tier-wide) ----------
    @property
    def brownout_level(self) -> int:
        return self._brownout

    def set_brownout(self, level: int,
                     shed_tenants=frozenset()) -> None:
        """Apply a brownout rung to every alive gateway (see
        ``Gateway.set_brownout``); remembered so revived gateways join
        at the tier's current level."""
        self._brownout = max(0, min(3, int(level)))
        self._shed_tenants = frozenset(shed_tenants)
        for gid in self.alive_ids():
            self.gateways[gid].set_brownout(
                self._brownout, shed_tenants=self._shed_tenants
            )

    # -- routing (the load-balancer stand-in) ------------------------------
    def gateway_for(self, request,
                    exclude: FrozenSet[str] = frozenset()) -> Optional[str]:
        """The request's home gateway: consistent hash of the session
        over the ALIVE gateway ids (so every client agrees, and a
        session keeps hitting the gateway that holds its admission
        context), round-robin for sessionless traffic."""
        with self._lock:
            alive = frozenset(
                gid for gid, gw in self.gateways.items() if gw.alive
            ) - exclude
            if not alive:
                return None
            session = getattr(request, "session", None)
            if session:
                self._ring.rebuild(alive)
                return self._ring.lookup(session)
            order = sorted(alive)
            self._rr += 1
            return order[self._rr % len(order)]

    # -- submission --------------------------------------------------------
    def submit(self, request: GatewayRequest,
               via: Optional[str] = None):
        """Admit through the home gateway (or ``via`` — any gateway can
        route any session).  Returns ``(gateway_id, PendingRequest)``.
        Attaches an abort event when the request has none: a gateway
        death must be able to cancel the request's attempts wire-level."""
        gid = via if via is not None else self.gateway_for(request)
        if gid is None:
            pending = PendingRequest(request.request_id)
            pending._resolve(GatewayResult(
                request.request_id, "error", error="no alive gateways",
            ))
            return "", pending
        if getattr(request, "abort", None) is None:
            request.abort = threading.Event()
        return gid, self.gateways[gid].submit(request)

    @staticmethod
    def _clone(request: GatewayRequest) -> GatewayRequest:
        """A fresh request object for a sibling retry: same identity and
        payload, NEW abort event (the dead gateway set the old one) and
        clean trace slot.  The streaming relay hooks carry over — the
        sibling continues the same caller's stream, and the relay's
        watermark rides ``stream_watermark`` so the dispatcher
        fast-forwards the resumed attempt."""
        clone = GatewayRequest(
            prompt=list(request.prompt),
            max_new_tokens=request.max_new_tokens,
            request_id=request.request_id,
            tenant=request.tenant,
            session=request.session,
            temperature=request.temperature,
            seed=request.seed,
            deadline_s=request.deadline_s,
        )
        clone.on_tokens = request.on_tokens
        clone.no_hedge = request.no_hedge
        clone.abort = threading.Event()
        wm = getattr(request, "stream_watermark", None)
        if wm is not None:
            clone.stream_watermark = wm
        return clone

    def submit_and_wait(self, request: GatewayRequest,
                        timeout: Optional[float] = None) -> GatewayResult:
        """The tier client contract: submit to the home gateway; on a
        GATEWAY death (not a request failure — those already failed over
        across replicas inside the gateway) retry the same request_id on
        the next gateway clockwise.  Exactly-once delivery holds because
        only this caller holds the handle chain, and the replica-side
        duplicate-id eviction keeps at most one live stream per
        request_id."""
        import time

        policy = self.policy or FailoverPolicy()
        deadline = time.monotonic() + (
            timeout
            if timeout is not None
            else (request.deadline_s or policy.deadline_s) + 5.0
        )
        tried: List[str] = []
        req = request
        last: Optional[GatewayResult] = None
        while True:
            gid = self.gateway_for(req, exclude=frozenset(tried))
            if gid is None:
                return last or GatewayResult(
                    request.request_id, "error",
                    error="no alive gateways",
                )
            gid, pending = self.submit(req, via=gid)
            remaining = deadline - time.monotonic()
            if not pending.wait(max(remaining, 0.0)):
                return GatewayResult(
                    request.request_id, "timeout",
                    error="gateway tier did not resolve in time",
                )
            result = pending.result()
            if is_gateway_death(result, self.gateways.get(gid)):
                tried.append(gid)
                last = result
                self.metrics.inc("gateway_tier_retries_total")
                req = self._clone(req)
                continue
            return result

    # -- views / delegation ------------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        ok = True
        for gw in self.gateways.values():
            if gw.alive:
                ok = gw.drain(timeout) and ok
        return ok

    def drain_replica(self, key: str, migrate: bool = True) -> dict:
        """Replica lifecycle through any alive gateway (the verbs act on
        the shared registry/client/store, so the choice is arbitrary)."""
        for gid in self.alive_ids():
            return self.gateways[gid].drain_replica(key, migrate=migrate)
        raise RuntimeError("no alive gateway to drain through")

    def results(self) -> Dict[str, GatewayResult]:
        """Merged terminal results, ALIVE gateways only — a real crash
        loses the dead process's result table, and the tier must not
        pretend otherwise (the client-side retry is the answer)."""
        out: Dict[str, GatewayResult] = {}
        for gid in self.alive_ids():
            out.update(self.gateways[gid].results())
        return out
