"""Bounded admission queue with per-tenant fairness and explicit backpressure.

The front door's first job is refusing work it cannot serve: an unbounded
queue converts overload into unbounded latency for EVERYONE (every queued
request eventually times out, after holding memory the whole wait).  This
queue is bounded twice — a global capacity and a per-tenant cap — and a
full queue raises ``QueueFull`` immediately, which the HTTP frontend maps
to 429 so clients back off instead of piling on.

Dequeue is round-robin across tenants with pending work (each tenant's own
requests stay FIFO): one tenant flooding its cap cannot starve another
tenant's single request behind its backlog.  This is the classic fair
front-door shape (c.f. WFQ in inference gateways); weightless round-robin
is enough at this queue's depth.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Deque, Dict, Optional


class QueueFull(Exception):
    """Explicit backpressure: the caller must surface this (HTTP 429),
    never swallow it — a silently dropped request is the failure mode the
    soak's I5 invariant hunts."""


class QueueClosed(Exception):
    pass


class AdmissionQueue:
    def __init__(self, capacity: int = 256,
                 per_tenant_cap: Optional[int] = None) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.per_tenant_cap = per_tenant_cap or capacity
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        # OrderedDict = round-robin ring: pop the first tenant with work,
        # re-append it after serving one request
        self._tenants: "OrderedDict[str, Deque]" = OrderedDict()
        self._depth = 0
        self._closed = False

    def put(self, request) -> None:
        """Admit or refuse NOW (no blocking): the producer is an HTTP
        handler thread that must answer its client either way."""
        tenant = getattr(request, "tenant", "") or ""
        with self._lock:
            if self._closed:
                raise QueueClosed("admission queue closed")
            if self._depth >= self.capacity:
                raise QueueFull(
                    f"queue at capacity ({self.capacity}); retry with backoff"
                )
            q = self._tenants.get(tenant)
            if q is None:
                q = deque()
                self._tenants[tenant] = q
            if len(q) >= self.per_tenant_cap:
                raise QueueFull(
                    f"tenant {tenant!r} at its cap ({self.per_tenant_cap})"
                )
            q.append(request)
            self._depth += 1
            # the admission-wait span: opened the moment the request is
            # durably queued, closed by get() when a dispatcher picks it
            # up — the span twin of gateway_queue_wait_seconds, but
            # per-request and in-tree
            trace = getattr(request, "trace", None)
            if trace is not None:
                request._admission_span = trace.child(
                    "admission_wait", tenant=tenant
                )
            self._not_empty.notify()

    def get(self, timeout: Optional[float] = None):
        """Next request, fair across tenants; None on timeout or close."""
        with self._not_empty:
            if self._depth == 0 and not self._closed:
                self._not_empty.wait(timeout)
            if self._depth == 0:
                return None
            # first tenant with work serves one request, then rotates to
            # the back of the ring; empty tenants fall out entirely
            for tenant in list(self._tenants):
                q = self._tenants[tenant]
                if not q:
                    del self._tenants[tenant]
                    continue
                req = q.popleft()
                self._depth -= 1
                del self._tenants[tenant]
                if q:
                    self._tenants[tenant] = q
                span = getattr(req, "_admission_span", None)
                if span is not None:
                    span.end()
                return req
            return None

    def depth(self) -> int:
        with self._lock:
            return self._depth

    def close(self) -> None:
        """Stop admitting; wake every blocked consumer."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
