"""ReplicaClient: the gateway's only data-plane dependency.

Mirrors the ApiServer pattern (SURVEY.md §4: every cluster dependency
behind an interface with an in-memory fake): the gateway dispatches
decode work through this interface and never opens a socket itself.

``InMemoryReplicaClient`` is the fake — one worker thread per replica
driving a batcher through the incremental serving API
(``submit``/``serve_step``/``cancel``), so e2e tests exercise the REAL
queue → route → admit → decode → retire path.  The batcher is duck-typed:
a real ``models.serving.ContinuousBatcher`` (true JAX decode, the e2e
wiring the tentpole demands) or a ``SimBatcher`` (pure-python token
mill) both fit — the soak and the 200-request acceptance test use the
latter so chaos runs stay fast and deterministic.

Failure model: ``fail_replica`` is the pod's process dying with its
chips — every in-flight attempt errors (the moral equivalent of a
connection reset), new submissions are refused.  ``sync_live`` wires
that to the registry's live-set subscription, so a chip death observed
by the control plane kills the data-plane connection the same cycle.
"""

from __future__ import annotations

import inspect
import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional

# the paged batchers' session-KV-reuse policy values and KV page-pool
# storage formats.  Canonically declared in models/serving.py
# (DECODE_PAGE_CACHE_POLICIES / KV_DTYPES); mirrored here because the
# gateway layer is deliberately jax-free and must not import the model
# stack for a few string tuples.  The pairs are pinned equal by
# tests/test_multiturn_kv.py and tests/test_quantized_pool.py.
DECODE_PAGE_CACHE_POLICIES = ("off", "fp32", "quantized", "all")
KV_DTYPES = ("bf16", "fp32", "int8")


def _sniff_takes(batcher, method: str, param: str) -> bool:
    """Does this batcher's ``method`` accept keyword ``param``?
    Duck-typed once per worker/serving-loop so third-party batchers
    without the kwarg still work.  Shared with the HTTP data plane
    (gateway/dataplane.py) so both drivers sniff identically."""
    try:
        fn = getattr(batcher, method)
        return param in inspect.signature(fn).parameters
    except (AttributeError, TypeError, ValueError):
        return False


def _sniff_takes_trace(batcher, method: str = "submit") -> bool:
    """Trace-context contract sniff (on ``submit`` or, for migration,
    ``import_pages``): requests on batchers without the kwarg simply
    serve untraced below the dispatch span."""
    return _sniff_takes(batcher, method, "trace")


def _payload_nbytes(payload: dict) -> int:
    """Approximate transfer size of a KV payload over the in-memory
    plane (no wire): the page/scale array bytes plus a nominal cursor
    overhead — the handoff wire-bytes metric's in-process stand-in,
    same order as the HTTP codec's encoded body."""
    total = 256   # cursor fields (tokens, keys, geometry) — nominal
    for sect in ("layers", "scales"):
        for k_arr, v_arr in payload.get(sect) or []:
            total += int(getattr(k_arr, "nbytes", 0) or 0)
            total += int(getattr(v_arr, "nbytes", 0) or 0)
    total += 4 * len(payload.get("tokens") or [])
    return total


def sim_stream_seed(prompt) -> int:
    """Request-deterministic stream seed for the SimBatcher mill.

    Real replicas serving the same weights emit the SAME greedy stream
    for the same prompt — the property hedged streaming's prefix dedup,
    sibling-gateway retries and mid-stream migrations all lean on.  The
    data planes model it by seeding the mill from the PROMPT (position-
    weighted so permutations differ) instead of the replica-local slot
    id: any replica, any resubmission, any continuation of the same
    request mills the same tokens.  Direct SimBatcher use without a
    ``stream_seed`` keeps the historical per-seq streams."""
    toks = [int(t) for t in prompt]
    return (
        len(toks) * 131
        + sum(t * (i + 1) for i, t in enumerate(toks))
    ) % 1000003


@dataclass
class AttemptResult:
    ok: bool
    tokens: List[int] = field(default_factory=list)
    error: str = ""


class Attempt:
    """Handle for one dispatch of one request to one replica.  Resolves
    exactly once; ``finish`` after the first call is a no-op (a cancelled
    attempt racing its own completion must not flap the result)."""

    def __init__(self, replica: str, request_id: str) -> None:
        self.replica = replica
        self.request_id = request_id
        self.cancelled = False
        # the request this attempt carries (stashed by the clients at
        # submit): live migration re-dispatches the SAME attempt handle
        # against a new replica, which needs the original request
        self.request = None
        # set while a live migration owns this attempt's resolution: the
        # exporter's stream ends with a "migrated" terminal the reader
        # must NOT surface as a failure
        self._migrating = False
        # set by the reader when it SEES that terminal — the handshake
        # migrate()'s export-failure path uses to resolve an attempt
        # whose sequence detached but whose export response was lost
        self._migrated_terminal = False
        # absolute token index this attempt's FIRST streamed delta
        # starts at: 0 normally; a hedge/retry fast-forwarded by a
        # resume watermark starts past the caller's delivered prefix
        # (set by the data-plane client that applied the watermark —
        # the StreamRelay indexes deltas with it)
        self.stream_base = 0
        # prefill/decode disaggregation: set by the replica when the
        # prompt's pages SEAL with zero tokens emitted (a prefill-only
        # batcher parked the sequence) — the dispatcher reacts by
        # handing the sequence off to a decode replica over the
        # migration verbs.  Sealed ⇒ parked ⇒ a handoff is REQUIRED:
        # the sequence decodes nowhere until one lands (or falls back)
        self.sealed = threading.Event()
        # where the handoff ended up: "" (none yet) | "ok" (decode
        # replica took it) | "fallback" (decode side refused/died;
        # the sequence resumed ON the prefill replica)
        self.handoff_outcome = ""
        self.handoff_wire_bytes = 0
        self._done = threading.Event()
        self._result: Optional[AttemptResult] = None
        self._lock = threading.Lock()

    def finish(self, result: AttemptResult) -> bool:
        with self._lock:
            if self._result is not None:
                return False
            self._result = result
        self._done.set()
        return True

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self) -> Optional[AttemptResult]:
        return self._result


class ReplicaClient:
    def submit(self, replica_key: str, request) -> Attempt:
        """Dispatch one request; never blocks on decode (returns a handle).
        An unreachable replica resolves the handle immediately with an
        error — connection refusal is a RESULT, not an exception."""
        raise NotImplementedError

    def cancel(self, attempt: Attempt) -> None:
        """Best-effort: stop decoding the attempt's request (hedge loser,
        expired deadline).  The attempt resolves with an error if it had
        not already finished."""
        raise NotImplementedError

    def ready(self) -> bool:
        """Can this client plausibly reach replicas at all?  /readyz ANDs
        this with replica discovery — a gateway whose data plane is a
        dead end must never join a Service, however many replicas the
        registry sees."""
        return True

    # -- KV-page migration (optional capability) ---------------------------
    # Data planes that speak the EXPORT/IMPORT verb pair override these;
    # the defaults say "unsupported" so drains degrade to the pre-verb
    # behavior (cold restarts) instead of erroring.

    def inflight_on(self, replica_key: str) -> List[Attempt]:
        """Live attempts currently dispatched to one replica — the drain
        path's work list."""
        return []

    def migrate(self, attempt: Attempt, request, to_key: str,
                _between: Optional[Callable[[], None]] = None,
                fallback: bool = False, cursor: int = 0) -> bool:
        """Move a live in-flight sequence to another replica: export +
        detach at the source, import + resume at the target; the SAME
        attempt handle keeps streaming and eventually resolves with the
        full token list.  False = migration not possible (the sequence
        stays where it was, or normal failover takes over).  ``_between``
        is a fault-injection hook invoked between the export and the
        import dispatch (the soak's kill-mid-migration schedules).
        ``fallback`` (the post-prefill handoff contract): an import
        refusal/death re-imports the held payload into the SOURCE and
        resumes decode there instead of erroring the attempt.
        ``cursor`` (the streamed handoff): the first ``cursor`` pages
        already shipped as acked deltas, so the final export carries
        keys for every page but bytes only from ``cursor`` on."""
        return False

    # -- streamed seal-time handoff (optional capability) ------------------
    # Data planes that can ship sealed pages DURING prefill override
    # these; the defaults say "unsupported", which degrades a handoff
    # to the one-shot post-seal transfer.

    def export_delta(self, attempt: Attempt, request,
                     cursor: int) -> Optional[dict]:
        """Pages of the attempt's sequence sealed since page index
        ``cursor`` (chain keys included), or None (nothing new sealed,
        sequence not streamable right now, or unsupported)."""
        return None

    def import_delta(self, replica_key: str, payload) -> Optional[int]:
        """Stage a streamed-handoff delta on a replica's prefix cache.
        Returns the staged-page count (an ACK — the exporter may
        reclaim through the delta's pages), or None when refused or
        unsupported (the dispatcher falls back to one-shot)."""
        return None

    def reclaim(self, attempt: Attempt, request, upto: int) -> int:
        """Release the attempt's first ``upto`` pages on its (parked)
        prefill replica — they were acked by the importer.  Returns
        pages freed; 0 = nothing to do or unsupported (best-effort)."""
        return 0

    def export_sealed(self, replica_key: str, stream) -> Optional[dict]:
        """Capture a finished stream's sealed prefix-chain pages from a
        replica (the failover insurance payload), or None."""
        return None

    def import_sealed(self, replica_key: str, payload) -> bool:
        """Warm a replica's prefix cache from a sealed-chain export."""
        return False

    def seals_decode(self, replica_key: str) -> bool:
        """Does this replica seal decode pages at retirement?  Gates the
        gateway's eager sealed-export captures (no point round-tripping
        a replica whose policy never seals)."""
        return False

    def set_speculation(self, cap: Optional[int]) -> int:
        """Brownout rung 2: cap (or restore, ``None``) speculative
        decode fleet-wide — the verify window's extra budget rows go
        back to admissions under overload.  Returns how many replicas
        actually adjusted; the default says "unsupported" (0), which
        degrades the rung to a no-op rather than an error.  Greedy
        output is lossless for ANY draft, so the cap never changes
        tokens — only per-step emission batching."""
        return 0


# ---------------------------------------------------------------------------
# SimBatcher: pure-python stand-in with the ContinuousBatcher serving API
# ---------------------------------------------------------------------------

class SimBatcher:
    """Deterministic token mill with the incremental serving API
    (submit/serve_step/cancel/has_work) but no JAX: token *i* of request
    *seq* is ``(seq * 31 + i) % vocab``, one token per serve_step per
    active sequence, ``slots`` sequences decode concurrently.  Lets soak
    and scale tests drive thousands of requests through the real gateway
    machinery in milliseconds.

    ``token_budget`` models the real batchers' token-budget step cap:
    at most that many sequences advance a token per serve_step, rotated
    round-robin so none starves (None = every active sequence advances,
    the historical behavior).  Per-sequence streams stay deterministic
    either way — token *i* depends only on (seq, i).

    ``speculate_k`` models draft-then-verify speculative decode: an
    advancing sequence emits a deterministic 1..k+1 tokens per step (the
    "accepted prefix" — a function of (seq, depth) only, so streams stay
    byte-identical to the non-speculative mill), and bills k+1 budget
    rows against ``token_budget`` whether or not the tail was accepted —
    exactly the paged scheduler's accounting (a speculative slot's
    verify window is k+1 rows wide regardless of acceptance).

    ``decode_page_cache`` is the paged batchers' session-KV-reuse policy
    ({"off", "fp32", "quantized", "all"}): the mill has no KV to seal,
    so it only validates the widened contract — a policy typo must die
    at replica construction here exactly as it would on a real batcher.

    ``kv_dtype`` is the paged batchers' page-pool storage format
    ({None, "bf16", "fp32", "int8"}): the mill stores no KV, so it
    validates the contract, advertises the format, and stamps it into
    its migration payloads — an importer on a different format refuses
    exactly like a real batcher's geometry check.

    ``submit(..., trace=)`` takes the caller's span context like the
    real batchers and emits the same minimal subtree (serve → queue →
    decode → retire), so gateway-level trace oracles (soak I5 from
    spans) run against the millisecond-fast mill too."""

    def __init__(self, slots: int = 8, vocab: int = 256,
                 token_budget: Optional[int] = None,
                 speculate_k: Optional[int] = None,
                 decode_page_cache: str = "off",
                 kv_dtype: Optional[str] = None,
                 tp: int = 1, prefill_only: bool = False) -> None:
        if token_budget is not None and token_budget <= 0:
            raise ValueError(
                f"token_budget ({token_budget}) must be positive or None"
            )
        if speculate_k is not None and speculate_k < 1:
            raise ValueError(
                f"speculate_k ({speculate_k}) must be >= 1 or None"
            )
        if decode_page_cache not in DECODE_PAGE_CACHE_POLICIES:
            raise ValueError(
                f"decode_page_cache must be one of "
                f"{DECODE_PAGE_CACHE_POLICIES}, got {decode_page_cache!r}"
            )
        if kv_dtype is not None and kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES} or None, got "
                f"{kv_dtype!r}"
            )
        if tp < 1:
            # the paged batchers' tensor-parallel width contract: the
            # mill has no mesh, so it only validates and ADVERTISES the
            # width (the /state replica_mesh surface) — a bad
            # value must die at replica construction here exactly as a
            # real batcher's mesh validation would
            raise ValueError(f"tp ({tp}) must be >= 1")
        self.slots = slots
        self.vocab = vocab
        self.token_budget = token_budget
        self.speculate_k = speculate_k
        # the CONFIGURED width; set_speculation_cap clamps/restores the
        # live speculate_k against it (brownout rung 2).  Token VALUES
        # are a function of (seed, index) only, so capping speculation
        # never changes a stream — just how many tokens a step emits.
        self._spec_configured = speculate_k
        self.decode_page_cache = decode_page_cache
        # canonical storage name for /state and migration payloads —
        # the REAL batcher advertises numpy-style names
        # ("bfloat16"/"float32"/"int8"), so the mill maps the CLI knob
        # the same way or a mixed fleet would read as a spurious
        # kv_dtype skew (the mill "computes" nothing; its full-width
        # twin is bf16)
        self.kv_dtype = {
            "bf16": "bfloat16", "fp32": "float32", "int8": "int8",
        }[kv_dtype or "bf16"]
        self.tp = tp
        self._pending: deque = deque()
        self._active: Dict[int, tuple] = {}  # seq -> (tokens, max_new)
        self._rr: deque = deque()            # active seqs in budget order
        self._spans: Dict[int, dict] = {}    # seq -> open span ctxs
        # migration keeps streams deterministic ACROSS replicas: token i
        # is a function of (seed, i), and the seed rides the export
        # payload — an imported sequence continues the ORIGINAL mill's
        # stream even though the importer assigned it a fresh seq id
        self._seed: Dict[int, int] = {}      # seq -> stream seed
        # prefill-only serving mode (disaggregation): the mill's
        # "prefill" is instant, so a fresh admission SEALS immediately
        # and PARKS — it never enters the decode ring until the gateway
        # exports it (drain_sealed surfaces the seq ids) or the mode
        # flips off.  Imported sequences bypass parking: the fallback
        # contract resumes decode HERE when the decode side refuses.
        self.prefill_only = bool(prefill_only)
        self._parked: set = set()            # parked (sealed) seq ids
        self._imported: set = set()          # seqs that arrived via import
        self._sealed_pending: List[int] = [] # sealed-but-unannounced
        # streamed-handoff twins: the mill's "sealed chain" is one sim
        # page per prompt token (no KV — pure cursor bookkeeping, so
        # the dispatcher's seal-watch/delta/ack/reclaim machinery runs
        # against the mill too)
        self._plen: Dict[int, int] = {}      # seq -> prompt length
        self._reclaimed: Dict[int, int] = {} # seq -> reclaim watermark
        self._staged: set = set()            # delta page keys staged here
        self.stats = {"steps": 0, "admits": 0, "imports": 0,
                      "pages_reclaimed": 0}

    def submit(self, seq_id: int, prompt, max_new: int,
               temperature: float = 0.0,
               session_id: Optional[str] = None, trace=None,
               stream_seed: Optional[int] = None,
               seed: Optional[int] = None) -> None:
        # session_id is the gateway's session/prefix key; the token mill
        # has no KV to reuse, so it only validates the widened contract.
        # stream_seed: the data planes pass sim_stream_seed(prompt) so
        # streams are REQUEST-deterministic (identical on any replica,
        # like real greedy decode); None keeps the per-seq mill.
        # seed: the caller's sampling pin — mixed into the mill seed so
        # a pinned sampled request mills the SAME stream on any replica
        # (the real batchers' position-keyed determinism, scaled down)
        # while different seeds mill different streams.
        if seq_id < 0:
            raise ValueError(f"seq_id must be >= 0, got {seq_id}")
        if trace is not None:
            old = self._spans.pop(seq_id, None)
            if old is not None:
                self._trace_end(old, "resubmitted")
            serve = trace.child("serve", seq_id=seq_id, sim=True)
            self._spans[seq_id] = {
                "serve": serve, "queue": serve.child("queue"),
            }
        try:
            self._plen[seq_id] = len(prompt)
        except TypeError:
            self._plen[seq_id] = 0
        mill = seq_id if stream_seed is None else int(stream_seed)
        if seed is not None:
            # Knuth-mix the pin so (prompt, seed) fully determines the
            # stream and seed=0 still perturbs the unpinned stream
            mill = (mill * 31 + int(seed) * 2654435761 + 1) % (2 ** 31)
        self._pending.append((seq_id, int(max_new), mill))

    def _trace_end(self, spans: dict, reason: str, **attrs) -> None:
        serve = spans.pop("serve")
        for span in spans.values():
            span.end()
        serve.event("retire", reason=reason, **attrs)
        serve.end()

    def trace_shutdown(self, reason: str = "replica died") -> None:
        # reason rides as `note` (the retire reason keys the documented
        # finished|cancelled|died enum; the note says WHICH replica)
        for seq in list(self._spans):
            self._trace_end(self._spans.pop(seq), "died", note=reason)

    def cancel(self, seq_id: int) -> bool:
        for i, (sid, *_rest) in enumerate(self._pending):
            if sid == seq_id:
                del self._pending[i]
                self._plen.pop(sid, None)
                if sid in self._spans:
                    self._trace_end(self._spans.pop(sid), "cancelled")
                return True
        if self._active.pop(seq_id, None) is None:
            return False
        # drop the ring entry too: a stale entry would double-count a
        # re-submitted seq_id against the budget forever (a PARKED
        # sequence never entered the ring — the export+detach path
        # cancels exactly these)
        if seq_id in self._parked:
            self._parked.discard(seq_id)
            if seq_id in self._sealed_pending:
                self._sealed_pending.remove(seq_id)
        else:
            self._rr.remove(seq_id)
        self._imported.discard(seq_id)
        self._seed.pop(seq_id, None)
        self._plen.pop(seq_id, None)
        self._reclaimed.pop(seq_id, None)
        if seq_id in self._spans:
            self._trace_end(self._spans.pop(seq_id), "cancelled")
        return True

    # -- KV migration twins (the paged batcher's verb pair, duck-typed):
    # the mill has no pages, so the payload is the stream cursor alone —
    # which is exactly what keeps soak streams deterministic across a
    # migration (token i depends only on (seed, i))
    def export_pages(self, seq_id: int, cursor: int = 0) -> dict:
        ent = self._active.get(seq_id)
        if ent is None:
            raise KeyError(f"sequence {seq_id} not active")
        tokens, max_new = ent
        # cursor (streamed handoff): the mill ships no bytes, so the
        # final export just records the offset — importers ignore it
        return {
            "kind": "live", "sim": True, "tokens": list(tokens),
            "max_new": int(max_new),
            "seed": int(self._seed.get(seq_id, seq_id)),
            "kv_dtype": self.kv_dtype, "layer_base": int(cursor),
        }

    def import_pages(self, seq_id: int, payload: dict,
                     trace=None) -> None:
        if payload.get("kind") != "live" or not payload.get("sim"):
            raise ValueError("not a sim-mill payload")
        if payload.get("kv_dtype", "bfloat16") != self.kv_dtype:
            # the real batchers' geometry refusal, mill-modeled: pages
            # stored in one format are not importable into another
            raise ValueError(
                f"transfer geometry mismatch on kv_dtype: payload "
                f"{payload.get('kv_dtype')!r} vs this batcher "
                f"{self.kv_dtype!r}"
            )
        if seq_id in self._active or any(
            sid == seq_id for sid, *_rest in self._pending
        ):
            raise ValueError(f"seq_id {seq_id} already in use")
        if len(self._active) >= self.slots:
            raise RuntimeError("import refused: no free slot")
        self._active[seq_id] = (
            list(payload["tokens"]), int(payload["max_new"])
        )
        self._seed[seq_id] = int(payload["seed"])
        # imported sequences DECODE even in prefill-only mode: the
        # handoff-fallback contract resumes a refused sequence on the
        # prefill replica that exported it
        self._imported.add(seq_id)
        self._rr.append(seq_id)
        self.stats["imports"] += 1
        if trace is not None:
            serve = trace.child("serve", seq_id=seq_id, sim=True,
                                imported=True)
            self._spans[seq_id] = {
                "serve": serve, "decode": serve.child("decode"),
            }

    def set_speculation_cap(self, cap: Optional[int]) -> bool:
        """Live speculation cap (brownout rung 2): ``None`` restores
        the configured width, 0 disables speculation, k clamps to
        min(configured, k).  Returns whether anything changed."""
        if self._spec_configured is None:
            return False
        if cap is None:
            new = self._spec_configured
        elif cap <= 0:
            new = None
        else:
            new = min(self._spec_configured, int(cap))
        changed = new != self.speculate_k
        self.speculate_k = new
        return changed

    # -- disaggregation verbs (duck-typed; the paged batcher's twins) ------
    def drain_sealed(self) -> List[int]:
        """Seq ids whose prompts sealed with zero tokens emitted since
        the last drain — the serving loop announces each one upstream
        exactly once (the dispatcher's handoff trigger)."""
        out, self._sealed_pending = self._sealed_pending, []
        return out

    def set_prefill_only(self, flag: bool) -> bool:
        """Flip the serving mode live (the controller's role actuator).
        Disabling UNPARKS every sealed sequence into the decode ring —
        collapse-to-colocated must never strand a parked stream.
        Mirror of the paged contract: a sequence whose handoff stream
        already RECLAIMED pages stays parked (its handoff completes or
        falls back through the import path instead)."""
        flag = bool(flag)
        changed = flag != self.prefill_only
        self.prefill_only = flag
        if not flag:
            keep = {s for s in self._parked if self._reclaimed.get(s)}
            for seq in sorted(self._parked - keep):
                if seq in self._active:
                    self._rr.append(seq)
            self._parked = keep
            self._sealed_pending = []
        return changed

    # -- streamed seal-time handoff twins ----------------------------------
    def export_sealed_delta(self, seq_id: int,
                            cursor: int) -> Optional[dict]:
        """The paged batcher's streaming export, mill-modeled: the sim
        chain is one page per prompt token (sealed in full the moment
        the sequence parks — the mill's prefill is instant), each page
        keyed by (stream seed, index) so staging dedups exactly like
        content addressing."""
        if seq_id not in self._active:
            raise KeyError(f"sequence {seq_id} not active")
        if seq_id not in self._parked:
            raise ValueError(f"sequence {seq_id} is decoding")
        n = self._plen.get(seq_id, 0)
        cursor = int(cursor)
        if cursor < 0 or cursor > n:
            raise ValueError(
                f"delta cursor {cursor} outside sealed bound {n}"
            )
        if cursor < self._reclaimed.get(seq_id, 0):
            raise ValueError(
                f"delta cursor {cursor} below reclaim watermark "
                f"{self._reclaimed[seq_id]}"
            )
        if cursor == n:
            return None
        seed = self._seed.get(seq_id, seq_id)
        return {
            "kind": "delta", "sim": True, "cursor": cursor,
            "page_keys": [f"{seed:x}:{j:x}" for j in range(cursor, n)],
            "sealed": True, "kv_dtype": self.kv_dtype,
        }

    def import_sealed_delta(self, payload: dict) -> int:
        """Stage a delta's sim pages (dedup by key, like the paged
        cache); the count returned is the exporter's ACK."""
        if payload.get("kind") != "delta" or not payload.get("sim"):
            raise ValueError("not a sim-mill delta payload")
        if payload.get("kv_dtype", "bfloat16") != self.kv_dtype:
            raise ValueError(
                f"transfer geometry mismatch on kv_dtype: payload "
                f"{payload.get('kv_dtype')!r} vs this batcher "
                f"{self.kv_dtype!r}"
            )
        fresh = [
            k for k in payload.get("page_keys") or []
            if k not in self._staged
        ]
        self._staged.update(fresh)
        return len(fresh)

    def reclaim_handoff_pages(self, seq_id: int, upto: int) -> int:
        """Advance a parked sequence's reclaim watermark (the mill has
        no pool — the counter IS the contract under test)."""
        if seq_id not in self._active:
            raise KeyError(f"sequence {seq_id} not active")
        if seq_id not in self._parked:
            return 0
        upto = min(int(upto), self._plen.get(seq_id, 0))
        freed = max(0, upto - self._reclaimed.get(seq_id, 0))
        if freed:
            self._reclaimed[seq_id] = upto
            self.stats["pages_reclaimed"] += freed
        return freed

    def assert_page_accounting(self) -> None:
        """The mill's invariant twin of the paged batcher's check —
        what the soak's both-ends oracle holds a sim replica to:
        every active sequence sits in exactly one of the decode ring
        or the parked set, every pending seal announcement names a
        parked sequence, and reclaim watermarks only ever cover a
        parked sequence's sealed sim chain."""
        ring = set(self._rr)
        for seq in self._active:
            parked = seq in self._parked
            assert parked != (seq in ring), (
                f"seq {seq}: parked={parked}, in_ring={seq in ring}"
            )
        for seq in self._sealed_pending:
            assert seq in self._parked, (
                f"seal announced for unparked seq {seq}"
            )
        for seq, upto in self._reclaimed.items():
            assert seq in self._parked, (
                f"reclaim watermark on unparked seq {seq}"
            )
            assert upto <= self._plen.get(seq, 0), (
                f"seq {seq} reclaimed {upto} past its chain"
            )

    def has_work(self) -> bool:
        return bool(self._pending) or bool(self._active)

    def live_tokens(self) -> Dict[int, List[int]]:
        """Committed tokens of every ACTIVE sequence (the real batchers'
        incremental-streaming surface): the HTTP serving loop reads this
        after each serve_step to emit one SSE event per committed token
        batch."""
        return {seq: list(t) for seq, (t, _) in self._active.items()}

    def serve_step(self) -> Dict[int, List[int]]:
        finished: Dict[int, List[int]] = {}
        while self._pending and len(self._active) < self.slots:
            seq, max_new, seed = self._pending.popleft()
            self.stats["admits"] += 1
            spans = self._spans.get(seq)
            if spans is not None and "queue" in spans:
                spans.pop("queue").end()
                if max_new > 0:
                    spans["decode"] = spans["serve"].child("decode")
            if max_new <= 0:
                if spans is not None:
                    self._trace_end(self._spans.pop(seq), "finished")
                self._plen.pop(seq, None)
                finished[seq] = []
            else:
                # a re-submitted still-active seq restarts its stream but
                # must NOT gain a second ring entry (double budget draw)
                park = self.prefill_only and seq not in self._imported
                in_ring = seq in self._active and seq not in self._parked
                if park and in_ring:
                    self._rr.remove(seq)
                elif not park and not in_ring:
                    self._rr.append(seq)
                if park:
                    # prefill-only: the mill's prefill is instant, so
                    # the prompt seals AT admission and the sequence
                    # parks awaiting its handoff export
                    self._parked.add(seq)
                    if seq not in self._sealed_pending:
                        self._sealed_pending.append(seq)
                else:
                    self._parked.discard(seq)
                self._active[seq] = ([], max_new)
                self._seed[seq] = seed
        if self._active:
            self.stats["steps"] += 1
            # parked sequences run ZERO decode steps: counting them
            # against the token budget would starve the ring of rows
            # they never use (the real batcher's admission budget
            # excludes parked slots the same way)
            n = sum(1 for s in self._active if s not in self._parked)
            if self.token_budget is not None:
                # a speculative sequence bills its whole k+1-row verify
                # window; at least one sequence always advances (the
                # real batchers' can't-starve floor)
                rows = (self.speculate_k or 0) + 1
                n = min(n, max(1, self.token_budget // rows))
            advanced = 0
            for _ in range(len(self._rr)):
                if advanced >= n:
                    break
                seq = self._rr.popleft()
                if seq not in self._active:
                    continue  # cancelled: drop its stale ring entry
                advanced += 1
                tokens, max_new = self._active[seq]
                seed = self._seed.get(seq, seq)
                if self.speculate_k is None:
                    emit = 1
                else:
                    # deterministic accepted-prefix length in [1, k+1],
                    # a function of (seed, depth) only: re-running the
                    # same request — or resuming it on another replica
                    # after a migration — yields the same emissions
                    emit = 1 + (seed * 7 + len(tokens)) % (
                        self.speculate_k + 1
                    )
                for _ in range(min(emit, max_new - len(tokens))):
                    tokens.append((seed * 31 + len(tokens)) % self.vocab)
                if len(tokens) >= max_new:
                    finished[seq] = tokens
                    del self._active[seq]
                    self._seed.pop(seq, None)
                    self._plen.pop(seq, None)
                    self._reclaimed.pop(seq, None)
                    if seq in self._spans:
                        self._trace_end(self._spans.pop(seq), "finished")
                else:
                    self._rr.append(seq)
        return finished


# ---------------------------------------------------------------------------
# InMemoryReplicaClient: per-replica worker threads over duck-typed batchers
# ---------------------------------------------------------------------------

class _ReplicaWorker:
    def __init__(self, key: str, batcher, step_delay_s: float) -> None:
        self.key = key
        self.batcher = batcher
        self.step_delay_s = step_delay_s
        self._takes_trace = _sniff_takes_trace(batcher)
        self._takes_stream_seed = _sniff_takes(
            batcher, "submit", "stream_seed"
        )
        self._takes_seed = _sniff_takes(batcher, "submit", "seed")
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.inbox: deque = deque()          # (attempt, request)
        self.cancels: List[Attempt] = []
        # control ops (export/import, sealed captures): closures run ON
        # the worker thread between steps — the batchers are
        # single-driver, and migration must never race serve_step
        self.ops: deque = deque()            # (fn, reply queue)
        # chaos knob: an armed worker refuses imports (the soak's
        # importer-refusal schedule; the HTTP twin is the worker CLI's
        # --serve-http-fail-migration)
        self.fail_migration = False
        self.alive = True
        self.by_seq: Dict[int, Attempt] = {}
        # streaming parity with the HTTP data plane: per-sequence token
        # sink + emitted watermark, fed from the batcher's live_tokens()
        # after each step so `on_tokens` callers see committed batches
        # from the in-memory plane too
        self.sinks: Dict[int, Callable] = {}
        self.emitted: Dict[int, int] = {}
        self._next_seq = 0
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self) -> None:
        while True:
            with self.cond:
                while (self.alive and not self.inbox and not self.cancels
                       and not self.ops and not self.batcher.has_work()):
                    self.cond.wait(0.05)
                if not self.alive:
                    dead = list(self.by_seq.values())
                    dead += [a for a, _ in self.inbox]
                    self.by_seq.clear()
                    self.inbox.clear()
                    # blocked control callers must not hang on a corpse
                    while self.ops:
                        _, reply = self.ops.popleft()
                        reply.put((False, RuntimeError(
                            f"replica {self.key} died"
                        )))
                    # the process dies with its spans: close every live
                    # request's subtree (retire reason "died") so the
                    # trace tree stays complete — the in-memory twin of
                    # a pod death ending its connections
                    shutdown = getattr(self.batcher, "trace_shutdown", None)
                    if shutdown is not None:
                        shutdown(f"replica {self.key} died")
                    break
                while self.inbox:
                    attempt, req = self.inbox.popleft()
                    dl = getattr(req, "deadline_s", None)
                    anchor = getattr(req, "enqueued_at", 0.0) or 0.0
                    if dl is not None and anchor and (
                        time.monotonic() >= anchor + dl
                    ):
                        # shed-before-work parity with the wire plane:
                        # a request that aged past its deadline in this
                        # worker's inbox is refused before any decode
                        attempt.finish(AttemptResult(
                            False, error="deadline expired before "
                            "admission (backpressure)",
                        ))
                        continue
                    seq = self._next_seq
                    self._next_seq += 1
                    kwargs = {"session_id": getattr(req, "session", None)}
                    if self._takes_trace:
                        kwargs["trace"] = getattr(req, "trace", None)
                    if self._takes_stream_seed:
                        # request-deterministic mill streams: any replica
                        # serves the same tokens for the same prompt,
                        # like real greedy decode (hedge dedup, tier
                        # retries and migrations all assume it)
                        kwargs["stream_seed"] = sim_stream_seed(req.prompt)
                    if (
                        self._takes_seed
                        and getattr(req, "seed", None) is not None
                    ):
                        kwargs["seed"] = int(req.seed)
                    try:
                        self.batcher.submit(
                            seq, req.prompt, req.max_new_tokens,
                            getattr(req, "temperature", 0.0),
                            **kwargs,
                        )
                        self.by_seq[seq] = attempt
                        sink = getattr(req, "on_tokens", None)
                        if sink is not None:
                            # resume watermark: a hedge/retry/failover
                            # re-dispatch fast-forwards its EMISSION
                            # past tokens the caller already holds —
                            # the sequence still decodes from 0
                            base = int(getattr(
                                req, "resume_watermark", 0
                            ) or 0)
                            attempt.stream_base = base
                            self.sinks[seq] = sink
                            self.emitted[seq] = base
                    except Exception as e:  # noqa: BLE001 - bad request
                        attempt.finish(AttemptResult(False, error=str(e)))
                for attempt in self.cancels:
                    for seq, a in list(self.by_seq.items()):
                        if a is attempt:
                            self.batcher.cancel(seq)
                            del self.by_seq[seq]
                            self.sinks.pop(seq, None)
                            self.emitted.pop(seq, None)
                    attempt.finish(
                        AttemptResult(False, error="cancelled")
                    )
                self.cancels.clear()
                while self.ops:
                    fn, reply = self.ops.popleft()
                    try:
                        reply.put((True, fn()))
                    except Exception as e:  # noqa: BLE001 - op result
                        reply.put((False, e))
            # decode OUTSIDE the lock: a slow step (real JAX dispatch)
            # must not block submission/cancel delivery
            finished = self.batcher.serve_step()
            self._flush_sinks()
            # disaggregation: announce freshly-sealed (parked) sequences
            # to their attempt handles — the dispatcher's handoff trigger
            drain = getattr(self.batcher, "drain_sealed", None)
            if drain is not None:
                for seq in drain():
                    a = self.by_seq.get(seq)
                    if a is not None:
                        a.sealed.set()
            for seq, tokens in finished.items():
                # flush the tail BEFORE dropping by_seq: the sink gets
                # its attempt handle alongside the final delta
                self._flush_one(seq, list(tokens))
                attempt = self.by_seq.pop(seq, None)
                self.sinks.pop(seq, None)
                self.emitted.pop(seq, None)
                if attempt is not None:
                    attempt.finish(AttemptResult(True, tokens=list(tokens)))
            if self.step_delay_s:
                time.sleep(self.step_delay_s)
        # crashed: every in-flight attempt sees a connection reset
        for attempt in dead:
            attempt.finish(
                AttemptResult(False, error=f"replica {self.key} died")
            )

    def _flush_one(self, seq: int, tokens) -> None:
        """Emit a sequence's unseen committed tokens into its sink."""
        sink = self.sinks.get(seq)
        if sink is None:
            return
        done = self.emitted.get(seq, 0)
        if len(tokens) > done:
            self.emitted[seq] = len(tokens)
            attempt = self.by_seq.get(seq)
            try:
                sink(attempt, list(tokens[done:]))
            except Exception:  # noqa: BLE001 - sink is advisory
                pass

    def _flush_sinks(self) -> None:
        if not self.sinks:
            return
        live = getattr(self.batcher, "live_tokens", None)
        if live is None:
            return
        for seq, tokens in live().items():
            self._flush_one(seq, tokens)

    def submit(self, attempt: Attempt, request) -> None:
        with self.cond:
            if not self.alive:
                attempt.finish(AttemptResult(
                    False, error=f"replica {self.key} unreachable"
                ))
                return
            self.inbox.append((attempt, request))
            self.cond.notify()

    def cancel(self, attempt: Attempt) -> None:
        with self.cond:
            self.cancels.append(attempt)
            self.cond.notify()

    def control(self, fn, timeout: float = 30.0):
        """Run a closure on the worker thread between steps; returns its
        value or re-raises its exception.  Raises RuntimeError when the
        worker is dead (the caller treats it like a connection error)."""
        reply: "_queue.Queue" = _queue.Queue(1)
        with self.cond:
            if not self.alive:
                raise RuntimeError(f"replica {self.key} unreachable")
            self.ops.append((fn, reply))
            self.cond.notify()
        ok, val = reply.get(timeout=timeout)
        if not ok:
            raise val
        return val

    def kill(self) -> None:
        with self.cond:
            self.alive = False
            self.cond.notify()


class InMemoryReplicaClient(ReplicaClient):
    def __init__(
        self,
        batcher_factory: Optional[Callable[[str], object]] = None,
        step_delay_s: float = 0.0,
    ) -> None:
        """``batcher_factory``: builds a fresh batcher for a replica key —
        used by ``add_replica`` when no batcher is passed, and by
        ``sync_live`` to model a pod restarting with a cold cache after
        its chips come back."""
        self.batcher_factory = batcher_factory
        self.step_delay_s = step_delay_s
        self._lock = threading.Lock()
        self._workers: Dict[str, _ReplicaWorker] = {}
        # the brownout rung-2 cap in force (None = none): a replica that
        # cold-restarts WHILE the fleet is browned out must come up
        # capped too — set_brownout only fires on level crossings, so
        # the client re-applies the remembered cap at worker bring-up
        self._spec_cap: Optional[int] = None
        # request_id -> completed decode deliveries (soak's wasted-hedge
        # and exactly-once accounting reads this)
        self.decodes: Dict[str, int] = {}

    # -- replica lifecycle -------------------------------------------------
    def add_replica(self, key: str, batcher=None,
                    step_delay_s: Optional[float] = None) -> None:
        if batcher is None:
            if self.batcher_factory is None:
                raise ValueError("no batcher and no batcher_factory")
            batcher = self.batcher_factory(key)
        with self._lock:
            old = self._workers.get(key)
            worker = _ReplicaWorker(
                key, batcher,
                self.step_delay_s if step_delay_s is None else step_delay_s,
            )
            self._workers[key] = worker
            cap = self._spec_cap
        if old is not None:
            old.kill()
        if cap is not None:
            fn = getattr(worker.batcher, "set_speculation_cap", None)
            if fn is not None:
                try:
                    worker.control(lambda fn=fn: fn(cap))
                except Exception:  # noqa: BLE001 - advisory knob
                    pass

    def fail_replica(self, key: str) -> None:
        with self._lock:
            worker = self._workers.pop(key, None)
        if worker is not None:
            worker.kill()

    def set_step_delay(self, key: str, delay_s: float) -> None:
        """Straggler injection: slow one replica's decode loop down."""
        with self._lock:
            worker = self._workers.get(key)
        if worker is not None:
            worker.step_delay_s = delay_s

    def sync_live(self, live: FrozenSet[str]) -> None:
        """Registry subscription hook: a replica leaving the live set is
        its process dying (in-flight work errors out); one re-entering
        restarts cold via the factory."""
        with self._lock:
            known = set(self._workers)
        for key in known - set(live):
            self.fail_replica(key)
        if self.batcher_factory is not None:
            for key in set(live) - known:
                self.add_replica(key)

    def replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._workers)

    def advertised(self) -> Dict[str, dict]:
        """Per-replica serving contract the data plane advertises
        upstream (duck-typed off each batcher): today the tensor-
        parallel width — a TP replica serves tp x the pool rows, which
        routing and autoscaling will want to weigh.  The ``GET /state``
        ``replica_mesh`` surface reads this."""
        with self._lock:
            workers = list(self._workers.items())
        return {
            key: {"tp": int(getattr(w.batcher, "tp", 1))}
            for key, w in workers
        }

    def ledgers(self, limit: int = 32) -> Dict[str, List[dict]]:
        """Recent per-iteration serving-ledger rows per replica, for
        batchers that keep one (duck-typed: the paged batcher's bounded
        ring; SimBatcher and the dense batcher have none).  The
        /debug/trace surface reads this."""
        with self._lock:
            workers = list(self._workers.items())
        out: Dict[str, List[dict]] = {}
        for key, w in workers:
            rows = getattr(w.batcher, "ledger_rows", None)
            if rows is not None:
                out[key] = rows(limit)
        return out

    def ready(self) -> bool:
        with self._lock:
            return bool(self._workers) or self.batcher_factory is not None

    def stop(self) -> None:
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            w.kill()

    # -- ReplicaClient -----------------------------------------------------
    def submit(self, replica_key: str, request) -> Attempt:
        attempt = Attempt(replica_key, request.request_id)
        attempt.request = request
        with self._lock:
            worker = self._workers.get(replica_key)
        if worker is None:
            attempt.finish(AttemptResult(
                False, error=f"replica {replica_key} unreachable"
            ))
            return attempt
        _original_finish = attempt.finish

        def counting_finish(result: AttemptResult) -> bool:
            first = _original_finish(result)
            if first and result.ok:
                with self._lock:
                    self.decodes[request.request_id] = (
                        self.decodes.get(request.request_id, 0) + 1
                    )
            return first

        attempt.finish = counting_finish  # type: ignore[method-assign]
        worker.submit(attempt, request)
        return attempt

    def cancel(self, attempt: Attempt) -> None:
        attempt.cancelled = True
        with self._lock:
            worker = self._workers.get(attempt.replica)
        if worker is not None:
            worker.cancel(attempt)
        else:
            attempt.finish(AttemptResult(False, error="cancelled"))

    # -- KV-page migration -------------------------------------------------
    def set_fail_migration(self, key: str, flag: bool) -> None:
        """Chaos knob: an armed replica refuses imports (the soak's
        importer-refusal schedule)."""
        with self._lock:
            worker = self._workers.get(key)
        if worker is not None:
            worker.fail_migration = flag

    def set_role(self, key: str, role: str) -> bool:
        """Disaggregation role actuator over the in-memory plane: flip
        one replica's batcher into (or out of) prefill-only serving
        mode, ON the serving thread.  Duck-typed — batchers without
        ``set_prefill_only`` (the dense batcher) stay co-located and
        report False.  The FleetController's ratio actuator pairs this
        with the registry's POD_ROLE annotation patch."""
        with self._lock:
            worker = self._workers.get(key)
        if worker is None:
            return False
        fn = getattr(worker.batcher, "set_prefill_only", None)
        if fn is None:
            return False
        try:
            worker.control(lambda: fn(role == "prefill"))
            return True
        except Exception:  # noqa: BLE001 - advisory knob
            return False

    def set_speculation(self, cap: Optional[int]) -> int:
        """Brownout rung 2 over the in-memory plane: apply a live
        speculation cap on every replica whose batcher supports one
        (duck-typed ``set_speculation_cap``), ON the serving thread —
        the batchers are single-driver."""
        with self._lock:
            self._spec_cap = cap
            workers = list(self._workers.values())
        adjusted = 0
        for w in workers:
            fn = getattr(w.batcher, "set_speculation_cap", None)
            if fn is None:
                continue
            try:
                if w.control(lambda fn=fn: fn(cap)):
                    adjusted += 1
            except Exception:  # noqa: BLE001 - advisory knob
                continue
        return adjusted

    def inflight_on(self, replica_key: str) -> List[Attempt]:
        with self._lock:
            worker = self._workers.get(replica_key)
        if worker is None:
            return []
        with worker.lock:
            return list(dict.fromkeys(worker.by_seq.values()))

    def seals_decode(self, replica_key: str) -> bool:
        with self._lock:
            worker = self._workers.get(replica_key)
        return worker is not None and bool(
            getattr(worker.batcher, "_seal_decode", False)
        )

    def export_sealed(self, replica_key: str, stream) -> Optional[dict]:
        with self._lock:
            worker = self._workers.get(replica_key)
        if worker is None or not hasattr(
            worker.batcher, "export_sealed_chain"
        ):
            return None
        try:
            return worker.control(
                lambda: worker.batcher.export_sealed_chain(stream)
            )
        except Exception:  # noqa: BLE001 - capture is best-effort
            return None

    def import_sealed(self, replica_key: str, payload) -> bool:
        if payload is None:
            return False
        with self._lock:
            worker = self._workers.get(replica_key)
        if worker is None or not hasattr(
            worker.batcher, "import_sealed_chain"
        ):
            return False
        try:
            return (
                worker.control(
                    lambda: worker.batcher.import_sealed_chain(payload)
                )
                or 0
            ) > 0
        except Exception:  # noqa: BLE001 - restore is best-effort
            return False

    # -- streamed seal-time handoff ----------------------------------------
    def export_delta(self, attempt: Attempt, request,
                     cursor: int) -> Optional[dict]:
        """Pages sealed since ``cursor`` on the attempt's replica, run
        ON its serving thread (read-only; single-driver batchers).
        None = nothing new, not streamable right now, or the replica
        is gone — streaming is best-effort by contract, so every
        failure mode degrades to the one-shot handoff."""
        with self._lock:
            src = self._workers.get(attempt.replica)
        if src is None or not hasattr(
            src.batcher, "export_sealed_delta"
        ):
            return None

        def op():
            seq = next(
                (s for s, a in src.by_seq.items() if a is attempt), None
            )
            if seq is None:
                return None
            return src.batcher.export_sealed_delta(seq, cursor)

        try:
            return src.control(op)
        except Exception:  # noqa: BLE001 - streaming is best-effort
            return None

    def import_delta(self, replica_key: str, payload) -> Optional[int]:
        """Stage a delta on the target's serving thread.  The returned
        count is the ACK the exporter's early reclaim keys off; None =
        refused (chaos knob, pool pressure, geometry) or unreachable —
        the dispatcher falls back to the one-shot transfer."""
        if payload is None:
            return None
        with self._lock:
            dst = self._workers.get(replica_key)
        if dst is None or not hasattr(
            dst.batcher, "import_sealed_delta"
        ):
            return None

        def op():
            if dst.fail_migration:
                raise RuntimeError("delta import refused (chaos knob)")
            return dst.batcher.import_sealed_delta(payload)

        try:
            return dst.control(op)
        except Exception:  # noqa: BLE001 - refusal = fall back
            return None

    def reclaim(self, attempt: Attempt, request, upto: int) -> int:
        with self._lock:
            src = self._workers.get(attempt.replica)
        if src is None or not hasattr(
            src.batcher, "reclaim_handoff_pages"
        ):
            return 0

        def op():
            seq = next(
                (s for s, a in src.by_seq.items() if a is attempt), None
            )
            if seq is None:
                return 0
            return src.batcher.reclaim_handoff_pages(seq, upto)

        try:
            return src.control(op) or 0
        except Exception:  # noqa: BLE001 - reclaim is best-effort
            return 0

    def migrate(self, attempt: Attempt, request, to_key: str,
                _between: Optional[Callable[[], None]] = None,
                fallback: bool = False, cursor: int = 0) -> bool:
        """Live migration over the in-memory plane: export + detach on
        the source worker's thread (atomic — no step can interleave),
        then import + re-register the SAME attempt on the target's.  A
        failed export leaves the sequence serving where it was; a
        failed import resolves the attempt with an error so normal
        failover re-dispatches it (cold — graceful, never wrong) —
        UNLESS ``fallback`` is set (the post-prefill handoff contract):
        then the held payload re-imports into the SOURCE, the sequence
        resumes decode where it prefilled, and the caller sees no error
        (``attempt.handoff_outcome`` says which way it went).  The
        source==target degenerate case is allowed under ``fallback``
        (detach-and-resume locally: a prefill replica with no decode
        peer unparks its own sequence through the same verb pair)."""
        with self._lock:
            src = self._workers.get(attempt.replica)
            dst = self._workers.get(to_key)
        if src is None or dst is None or attempt.done:
            return False
        if src is dst and not fallback:
            return False
        if not hasattr(src.batcher, "export_pages") or not hasattr(
            dst.batcher, "import_pages"
        ):
            return False
        trace = getattr(request, "trace", None)
        # overhang_ok: the migrated continuation's serve subtree nests
        # under this span, and its teardown may land after a hedge twin
        # already closed the request root — the same asynchrony the
        # dispatch spans carry
        mspan = (
            trace.child("migrate", source=attempt.replica, target=to_key,
                        overhang_ok=True)
            if trace is not None else None
        )
        attempt._migrating = True

        def export_op():
            seq = next(
                (s for s, a in src.by_seq.items() if a is attempt), None
            )
            if seq is None:
                raise KeyError("attempt not live on the source")
            payload = (src.batcher.export_pages(seq, cursor)
                       if cursor else src.batcher.export_pages(seq))
            # flush any tokens the export's pipeline drain just
            # committed, so the streaming relay misses nothing
            src._flush_sinks()
            src.batcher.cancel(seq)
            del src.by_seq[seq]
            src.sinks.pop(seq, None)
            src.emitted.pop(seq, None)
            return payload

        try:
            payload = src.control(export_op)
        except Exception:  # noqa: BLE001 - export failure = no migration
            if mspan is not None:
                mspan.end(outcome="export_failed")
            return False
        if _between is not None:
            _between()   # fault injection: kill-mid-migration schedules

        attempt.handoff_wire_bytes = _payload_nbytes(payload)

        def import_into(w: "_ReplicaWorker", chaos: bool):
            def op():
                if chaos and w.fail_migration:
                    raise RuntimeError("migration refused (chaos knob)")
                seq = w._next_seq
                w._next_seq += 1
                # the continuation's serve subtree nests under the
                # migrate span (overhang-exempt): its teardown may
                # legitimately outlive the request root
                w.batcher.import_pages(seq, payload, trace=mspan)
                w.by_seq[seq] = attempt
                sink = getattr(request, "on_tokens", None)
                if sink is not None:
                    w.sinks[seq] = sink
                    w.emitted[seq] = len(payload.get("tokens") or [])

            w.control(op)

        try:
            import_into(dst, chaos=True)
        except Exception as e:  # noqa: BLE001 - import failure = result
            if fallback:
                # the handoff-fallback contract: the decode side refused
                # (pool pressure, dtype skew, chaos) or died between
                # export and import ack — resume decode ON the source.
                # The source's import_pages marks the sequence imported,
                # so a prefill-only batcher decodes it instead of
                # re-parking (never a request error).
                try:
                    import_into(src, chaos=False)
                except Exception as e2:  # noqa: BLE001 - now it IS one
                    attempt.finish(AttemptResult(
                        False,
                        error=f"migration import failed: {e}; "
                              f"fallback failed: {e2}",
                    ))
                    if mspan is not None:
                        mspan.end(outcome="fallback_failed")
                    return False
                attempt.handoff_outcome = "fallback"
                if mspan is not None:
                    mspan.end(outcome="fallback",
                              pages=len(payload.get("page_keys") or []))
                return True
            attempt.finish(AttemptResult(
                False, error=f"migration import failed: {e}"
            ))
            if mspan is not None:
                mspan.end(outcome="import_failed")
            return False
        attempt.replica = to_key
        if fallback:
            # a post-prefill handoff landed; src==dst (the collapse
            # rung's local unpark) crossed no replica boundary and must
            # not read as a disaggregated handoff.  Plain migrations
            # (drains, the soak's bare op) leave the outcome untouched.
            attempt.handoff_outcome = "fallback" if src is dst else "ok"
        if mspan is not None:
            mspan.end(outcome="ok",
                      pages=len(payload.get("page_keys") or []))
        return True
