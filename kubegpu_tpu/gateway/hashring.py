"""Consistent hashing: the tier's coordination-free routing agreement.

With N gateways fronting one replica fleet, every gateway must route a
session to the SAME replica without talking to its siblings — a shared
pin table would be a coordination point and a single point of failure,
which is exactly what the tier exists to remove.  A consistent-hash
ring over the replica keys gives that agreement for free: the ring is a
pure function of the membership set (which every gateway already
observes through the shared registry view), so two gateways that see
the same replicas route every session identically, and a gateway that
has never seen a session routes it exactly where its sibling did.

The classic ring properties are the failover story:

- **stability**: a key's owner never changes while its owner stays in
  the ring — adding or removing OTHER nodes cannot move it;
- **bounded movement**: removing a node moves only the keys it owned
  (~1/N of them), each to the next node clockwise; adding a node steals
  ~1/N of the keyspace and nothing else.  Every moved session is a
  "mispin" the SessionKVStore restore path turns into a KV transfer
  instead of a cold prefill.

Hashes are ``hashlib`` (sha1), never Python ``hash()``: the ring must
agree ACROSS PROCESSES, and ``PYTHONHASHSEED`` randomizes ``hash()``
per interpreter.  ``vnodes`` virtual points per node smooth the
keyspace split (the standard variance fix; 64 keeps the max/min owned
fraction within ~2x for small fleets).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import FrozenSet, Iterable, List, Optional, Tuple


def _point(s: str) -> int:
    """Stable 64-bit ring coordinate for a string."""
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8], "big")


class ConsistentHashRing:
    """An immutable-by-convention hash ring: ``rebuild`` swaps the whole
    membership (the registry hands us snapshots, not deltas), ``lookup``
    walks clockwise from the key's point to the first non-excluded
    node.  Not thread-safe on its own — callers swap whole instances or
    hold their own lock (the routers do the latter)."""

    def __init__(self, nodes: Iterable[str] = (), vnodes: int = 64) -> None:
        if vnodes < 1:
            raise ValueError(f"vnodes ({vnodes}) must be >= 1")
        self.vnodes = vnodes
        self._nodes: FrozenSet[str] = frozenset()
        self._points: List[Tuple[int, str]] = []
        self._keys: List[int] = []
        self.rebuild(nodes)

    def rebuild(self, nodes: Iterable[str]) -> None:
        nodes = frozenset(nodes)
        if nodes == self._nodes:
            return
        points: List[Tuple[int, str]] = []
        for node in nodes:
            for i in range(self.vnodes):
                # ties on a point are broken by node name so the ring is
                # total and identical on every gateway
                points.append((_point(f"{node}#{i}"), node))
        points.sort()
        self._nodes = nodes
        self._points = points
        self._keys = [p for p, _ in points]

    def nodes(self) -> FrozenSet[str]:
        return self._nodes

    def lookup(self, key: str,
               exclude: FrozenSet[str] = frozenset()) -> Optional[str]:
        """The node owning ``key``; with ``exclude``, the first DISTINCT
        non-excluded node clockwise — the deterministic retry/hedge
        order every gateway agrees on.  None when everything is
        excluded (or the ring is empty)."""
        if not self._points:
            return None
        start = bisect.bisect_left(self._keys, _point(key)) % len(self._points)
        seen = set()
        for off in range(len(self._points)):
            node = self._points[(start + off) % len(self._points)][1]
            if node in seen:
                continue
            seen.add(node)
            if node not in exclude:
                return node
            if len(seen) == len(self._nodes):
                return None
        return None

    def preference(self, key: str, n: Optional[int] = None) -> List[str]:
        """The first ``n`` distinct nodes clockwise from ``key`` — the
        tier client's gateway failover order (home first, then the
        sibling every OTHER client would also pick next)."""
        if not self._points:
            return []
        limit = len(self._nodes) if n is None else min(n, len(self._nodes))
        start = bisect.bisect_left(self._keys, _point(key)) % len(self._points)
        out: List[str] = []
        for off in range(len(self._points)):
            node = self._points[(start + off) % len(self._points)][1]
            if node not in out:
                out.append(node)
                if len(out) >= limit:
                    break
        return out
