"""Routing policies: pick a replica for a request.

Pluggable (the scheduler's plugin-registry spirit applied to the data
plane): a ``Router`` sees the live replica set, the gateway's outstanding
per-replica request counts, and an exclude set (replicas this request
already failed or hedged on), and returns one replica or None.

- ``LeastOutstandingRouter`` (default): classic least-outstanding-requests.
  Queue depth at the replica is the best cheap congestion signal a gateway
  has (better than round-robin under heterogeneous request cost, no
  replica-side cooperation needed).  Ties break by ICI slice locality —
  prefer the slice the session's KV history lives on (a same-slice re-route
  after failover keeps any future KV migration on ICI instead of DCN),
  then by mesh distance within the slice, then by name for determinism.
- ``SessionAffinityRouter``: sticky session → replica mapping for KV
  reuse (a replica that served a session's earlier turns still holds the
  conversation's cache pages).  Falls back to least-outstanding — with the
  dead replica's slice as the locality hint — when the pinned replica
  drains, and re-pins to the new choice.
- ``PrefixLocalityRouter``: route by longest LOCALLY-cached prefix (PR
  16's fleet-wide prefix tier keeps an advisory warmth map of which
  sealed chains live on which replica), so an agent fleet sharing one
  scaffold packs onto warm replicas instead of spraying cold imports.
  Ties break by least-outstanding among the equally-warm; no warmth at
  all falls back to the consistent-hash ring — which keeps session
  routing deterministic across gateways, and keeps follow turns sticky
  anyway (the session's own replica holds its longest chain).
- ``ConsistentHashRouter``: the multi-gateway tier's affinity policy —
  session → replica via a consistent-hash ring over the routable replica
  keys.  Routing is a pure function of (session, membership), so N
  gateways sharing one registry view route every session IDENTICALLY
  with zero coordination: any gateway can route any session, and a
  sibling taking over a crashed gateway's sessions lands them on the
  same replicas.  Membership changes move only the ring-bounded key
  fraction; each moved session is a "mispin" the dispatcher's
  SessionKVStore restore turns into a KV transfer.
"""

from __future__ import annotations

import threading
from typing import Dict, FrozenSet, List, Mapping, Optional

from kubegpu_tpu.gateway.hashring import ConsistentHashRing
from kubegpu_tpu.gateway.registry import ReplicaInfo
from kubegpu_tpu.types.topology import coords_bounding_box
from kubegpu_tpu.utils.metrics import Metrics


class Router:
    def pick(
        self,
        request,
        replicas: List[ReplicaInfo],
        outstanding: Mapping[str, int],
        exclude: FrozenSet[str] = frozenset(),
    ) -> Optional[ReplicaInfo]:
        raise NotImplementedError


def _phase_candidates(candidates: List[ReplicaInfo]) -> List[ReplicaInfo]:
    """Phase-aware narrowing for FRESH submissions (= the prefill
    phase) in a role-split fleet: prefill-role replicas take them when
    any are routable (shortest-queue/warmth ranking then applies WITHIN
    the prefill pool), and decode-role replicas are avoided while a
    flex replica stands — decode capacity is reserved for handed-off
    sequences.  An all-decode candidate set still serves (availability
    over purity: a lone surviving replica takes the request co-located
    rather than refusing it).  Uniform fleets (every role "flex", the
    default) pass through unchanged."""
    pref = [r for r in candidates
            if getattr(r, "role", "flex") == "prefill"]
    if pref:
        return pref
    flex = [r for r in candidates
            if getattr(r, "role", "flex") != "decode"]
    return flex or candidates


def _mesh_distance(a: ReplicaInfo, b: ReplicaInfo) -> int:
    """Manhattan distance between the two replicas' chip-block origins —
    the ICI hop-count proxy the contiguity scorer uses; only meaningful
    within one slice."""
    if not a.coords or not b.coords:
        return 0
    ao, _ = coords_bounding_box(a.coords)
    bo, _ = coords_bounding_box(b.coords)
    return sum(abs(x - y) for x, y in zip(ao, bo))


def handoff_rank_key(candidate: ReplicaInfo,
                     anchor: Optional[ReplicaInfo],
                     outstanding: Mapping[str, int]):
    """Adjacency score for prefill→decode pair selection (grpalloc-style
    locality from the registry's topology annotations): same slice as
    the anchor first — handoff bytes ride ICI, not DCN — then mesh
    distance within the slice, then load, then key for determinism.
    Lower sorts first; shared between the dispatcher's pre-seal target
    pick (the delta stream's destination) and the seal-time handoff
    rank, so the streamed target and the final-hop target agree."""
    same_slice = (
        anchor is not None and candidate.slice_id == anchor.slice_id
    )
    return (
        0 if same_slice else 1,
        _mesh_distance(candidate, anchor) if same_slice else 0,
        outstanding.get(candidate.key, 0),
        candidate.key,
    )


class LeastOutstandingRouter(Router):
    def pick(self, request, replicas, outstanding, exclude=frozenset()):
        candidates = _phase_candidates(
            [r for r in replicas if r.key not in exclude]
        )
        if not candidates:
            return None
        hint_slice = getattr(request, "preferred_slice", None)
        hint_replica = getattr(request, "preferred_replica", None)
        anchor = next(
            (r for r in replicas if r.key == hint_replica), None
        )

        def rank(r: ReplicaInfo):
            # smaller is better on every component; name last makes the
            # whole ordering total and deterministic
            return (
                outstanding.get(r.key, 0),
                0 if (hint_slice and r.slice_id == hint_slice) else 1,
                _mesh_distance(r, anchor) if (
                    anchor is not None and r.slice_id == anchor.slice_id
                ) else 0,
                r.key,
            )

        return min(candidates, key=rank)


class SessionAffinityRouter(Router):
    """Sticky sessions over a fallback router (LeastOutstanding default).

    The pin map is bounded: entries for sessions nobody re-requests age
    out FIFO past ``max_sessions`` — an affinity table must not grow with
    total session history.

    ``metrics``: a re-pin after a pinned replica dies is a KV-loss event
    (the session's cached prefix pages die with the replica, and the new
    replica's ``prefix_hit_tokens`` start from zero) — counted as
    ``gateway_session_repin_total`` so operators see affinity churn next
    to the hit-rate it costs.  ``Gateway`` wires its own registry in when
    the caller didn't.
    """

    def __init__(self, fallback: Optional[Router] = None,
                 max_sessions: int = 65536,
                 metrics: Optional[Metrics] = None) -> None:
        self.fallback = fallback or LeastOutstandingRouter()
        self.max_sessions = max_sessions
        self.metrics = metrics
        self._lock = threading.Lock()
        self._pins: Dict[str, str] = {}  # session -> replica key
        # each pinned replica's slice, so a DEAD pin can still hint
        # locality (the dead replica no longer appears in `replicas`).
        # Same FIFO bound as the pins: one entry per replica key ever
        # pinned would otherwise leak across replica pod churn
        self._last_slices: Dict[str, Optional[str]] = {}

    def pick(self, request, replicas, outstanding, exclude=frozenset()):
        session = getattr(request, "session", None)
        # the dispatcher's open route span, when tracing: the pin
        # decision lands in the request's tree (pinned hit vs re-pin is
        # exactly the "why was turn-2 TTFT cold" answer)
        route_span = getattr(request, "route_span", None)
        if not session:
            return self.fallback.pick(request, replicas, outstanding, exclude)
        with self._lock:
            pinned = self._pins.get(session)
        by_key = {r.key: r for r in replicas}
        if pinned and pinned in by_key and pinned not in exclude:
            if route_span is not None:
                route_span.annotate(session=session, pinned=pinned,
                                    repin=False)
            return by_key[pinned]
        # pinned replica drained (or first sighting): route by load, with
        # the old pin as the locality hint so the replacement stays on the
        # slice the session's KV lived on where possible
        if pinned is not None:
            request = _with_hint(request, pinned, self._slice_of(pinned))
        choice = self.fallback.pick(request, replicas, outstanding, exclude)
        if choice is not None:
            if route_span is not None:
                route_span.annotate(
                    session=session, repin=pinned is not None,
                    lost_pin=pinned or "",
                )
            if pinned is not None and self.metrics is not None:
                # the session HAD a pin and lost it: its KV history is
                # gone wherever the old replica went
                self.metrics.inc("gateway_session_repin_total")
            with self._lock:
                self._pins[session] = choice.key
                self._last_slices[choice.key] = choice.slice_id
                while len(self._pins) > self.max_sessions:
                    self._pins.pop(next(iter(self._pins)))
                while len(self._last_slices) > self.max_sessions:
                    self._last_slices.pop(next(iter(self._last_slices)))
        return choice

    def _slice_of(self, replica_key: str) -> Optional[str]:
        return self._last_slices.get(replica_key)

    def forget(self, session: str) -> None:
        with self._lock:
            self._pins.pop(session, None)

    def pins_on(self, replica_key: str) -> List[str]:
        """Sessions currently pinned to one replica — the drain path's
        capture list (their sealed KV should be exported before the
        replica is released)."""
        with self._lock:
            return [
                s for s, k in self._pins.items() if k == replica_key
            ]

    def forget_replica(self, replica_key: str) -> None:
        """Drop every pin to a draining/released replica.  A PLANNED
        unpin: the next turn re-pins by load (with the sealed-export
        restore making the move a transfer) and is not counted as a
        KV-loss re-pin — the loss metric is for replicas that die out
        from under their sessions."""
        with self._lock:
            for s in [
                s for s, k in self._pins.items() if k == replica_key
            ]:
                del self._pins[s]


class ConsistentHashRouter(Router):
    """Session → replica by consistent hashing over routable replicas.

    Stateless where it matters: the ring is rebuilt from whatever
    replica list the dispatcher passes (the shared registry view), so
    every gateway instance holding its own ``ConsistentHashRouter``
    computes the same route — the tier's no-coordination guarantee.
    The only retained state is a bounded last-route memo used to COUNT
    movement (``gateway_session_repin_total`` when a session's ring
    target changed — the KV either moved with it via the sealed-export
    restore or re-prefills cold) and to annotate route spans.

    Sessionless requests fall back (LeastOutstanding default); the
    exclude set walks the ring clockwise, so retries/hedges visit
    replicas in the same deterministic order on every gateway.
    """

    def __init__(self, fallback: Optional[Router] = None,
                 vnodes: int = 64, max_sessions: int = 65536,
                 metrics: Optional[Metrics] = None) -> None:
        self.fallback = fallback or LeastOutstandingRouter()
        self.metrics = metrics
        self.max_sessions = max_sessions
        self._lock = threading.Lock()
        self._ring = ConsistentHashRing(vnodes=vnodes)
        self._last_route: Dict[str, str] = {}  # session -> replica key

    def pick(self, request, replicas, outstanding, exclude=frozenset()):
        session = getattr(request, "session", None)
        if not session:
            return self.fallback.pick(request, replicas, outstanding,
                                      exclude)
        by_key = {r.key: r for r in replicas}
        route_span = getattr(request, "route_span", None)
        # an exclude-walk (hedge twin, retry probe) is NOT a route
        # change: the session's ring home is unchanged — it must
        # neither overwrite the movement memo nor count as a repin
        probing = bool(exclude)
        with self._lock:
            self._ring.rebuild(by_key)
            target = self._ring.lookup(session, exclude=frozenset(exclude))
            prev = self._last_route.get(session)
            if target is not None and not probing:
                self._last_route[session] = target
                while len(self._last_route) > self.max_sessions:
                    self._last_route.pop(next(iter(self._last_route)))
        if target is None:
            return None
        moved = not probing and prev is not None and prev != target
        if route_span is not None:
            route_span.annotate(session=session, ring=True,
                                repin=moved, lost_pin=prev or "")
        if moved and self.metrics is not None:
            # the ring target changed (membership churn): the session's
            # KV is elsewhere — the sealed-export restore decides
            # whether that is a transfer or a cold prefill
            self.metrics.inc("gateway_session_repin_total")
        return by_key[target]

    def forget_replica(self, replica_key: str) -> None:
        """Drain bookkeeping parity with SessionAffinityRouter: drop the
        movement memos pointing at a released replica so its eventual
        ring re-entry (same pod name, fresh process) is not counted as
        a second move."""
        with self._lock:
            for s in [
                s for s, k in self._last_route.items() if k == replica_key
            ]:
                del self._last_route[s]


class PrefixLocalityRouter(Router):
    """Prefix-locality routing over the fleet-wide prefix tier.

    Scores every routable replica by how many pages of the request's
    prompt are already warm there (``PrefixTier.locality_scores`` — the
    advisory warmth map fed by sealed-here/imported-here events) and
    routes to the warmest; equal warmth breaks by least-outstanding,
    zero warmth falls back (``ConsistentHashRouter`` default, so
    sessionful traffic stays ring-deterministic tier-wide).

    The warmth map is ADVISORY: a stale score routes one request to a
    replica that then probes the tier or prefills cold — a perf blip,
    never wrong tokens, because the replica's content-keyed cache is
    the ground truth at admission.  ``forget_replica`` drops both the
    tier's warmth and the fallback's memos, which also keeps the
    dispatcher's mispin-restore duck-typing intact."""

    def __init__(self, tier, fallback: Optional[Router] = None,
                 metrics: Optional[Metrics] = None) -> None:
        self.tier = tier
        self.fallback = fallback or ConsistentHashRouter(metrics=metrics)
        self.metrics = metrics

    def pick(self, request, replicas, outstanding, exclude=frozenset()):
        candidates = _phase_candidates(
            [r for r in replicas if r.key not in exclude]
        )
        if not candidates:
            return None
        prompt = getattr(request, "prompt", None)
        scores: Mapping[str, int] = {}
        if prompt:
            scores = self.tier.locality_scores(
                prompt, [r.key for r in candidates]
            )
        best = max(scores.values(), default=0)
        if best > 0:
            warm = [r for r in candidates if scores.get(r.key, 0) == best]
            choice = min(
                warm, key=lambda r: (outstanding.get(r.key, 0), r.key)
            )
            route_span = getattr(request, "route_span", None)
            if route_span is not None:
                route_span.annotate(prefix_locality=True,
                                    warm_pages=best)
            if self.metrics is not None:
                self.metrics.inc("gateway_prefix_route_warm_total")
            return choice
        return self.fallback.pick(request, replicas, outstanding, exclude)

    def forget_replica(self, replica_key: str) -> None:
        self.tier.forget_replica(replica_key)
        forget = getattr(self.fallback, "forget_replica", None)
        if forget is not None:
            forget(replica_key)


class _with_hint:
    """Request view carrying a routing hint without mutating the caller's
    request object (dispatch may retry with the original)."""

    def __init__(self, request, preferred_replica, preferred_slice):
        self._request = request
        self.preferred_replica = preferred_replica
        self.preferred_slice = preferred_slice

    def __getattr__(self, name):
        return getattr(self._request, name)
