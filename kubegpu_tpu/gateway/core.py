"""Gateway core: admit → route → dispatch → deliver, no sockets.

The cluster front door as pure orchestration (the scheduler split applied
to the data plane: this module is to ``gateway/server.py`` what
``scheduler/core.py`` is to ``scheduler/server.py``).  A request's life:

    submit() ── bounded AdmissionQueue (QueueFull → "rejected", HTTP 429)
             ── dispatcher thread picks it up (queue-wait histogram)
             ── Dispatcher drives route → attempt → hedge/retry (failover)
             ── exactly-once result recording (duplicate deliveries from
                hedge races are counted and DROPPED, never surfaced)

Every admitted request reaches exactly one terminal result — "ok",
"error" or "timeout" — and a refused one is rejected explicitly at
submission.  That accounting IS the soak invariant I5; the gateway
enforces it structurally (single-writer result slot) rather than hoping
the failover logic never races.

Latency metrics: responses are unary (the ReplicaClient returns the full
completion), so time-to-first-token and time-to-last-token coincide;
``gateway_ttft_seconds`` records enqueue → response.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubegpu_tpu.gateway.client import ReplicaClient
from kubegpu_tpu.gateway.failover import (
    Dispatcher,
    FailoverPolicy,
    SessionKVStore,
)
from kubegpu_tpu.gateway.queue import AdmissionQueue, QueueClosed, QueueFull
from kubegpu_tpu.gateway.registry import ReplicaRegistry
from kubegpu_tpu.gateway.router import LeastOutstandingRouter, Router
from kubegpu_tpu.utils.metrics import Metrics, default_metrics
from kubegpu_tpu.utils.tracing import Tracer

log = logging.getLogger(__name__)


@dataclass
class GatewayRequest:
    prompt: list
    max_new_tokens: int
    request_id: str = ""
    tenant: str = ""
    session: Optional[str] = None
    temperature: float = 0.0
    # seed-pinned sampling: replicas derive sample keys from
    # (seed, absolute token position) — any replica/slot/batch/restart
    # reproduces the same stream, so hedging, resume dedup, migration
    # and disaggregated handoff stay sound for sampled traffic
    seed: Optional[int] = None
    deadline_s: Optional[float] = None   # per-request override
    enqueued_at: float = 0.0             # stamped by submit()
    # runtime trace context (utils.tracing.SpanCtx), stamped by submit()
    # when the gateway traces; carried down through queue -> dispatch ->
    # replica batcher so the whole request is ONE span tree
    trace: Optional[object] = field(default=None, repr=False, compare=False)
    # streaming sink: a data-plane client that streams (HttpReplicaClient)
    # calls on_tokens(attempt, delta) for every committed token batch —
    # the gateway's SSE pass-through feeds its caller from this.  The
    # terminal result's token list remains authoritative (a hedge's
    # winner may differ from the attempt that streamed).
    on_tokens: Optional[object] = field(default=None, repr=False,
                                        compare=False)
    # caller-abort signal (threading.Event): set when the downstream
    # client vanished mid-stream; the dispatcher cancels every in-flight
    # attempt (wire-level, pages freed replica-side) and resolves the
    # request with an explicit error
    abort: Optional[object] = field(default=None, repr=False, compare=False)
    # streaming requests never hedge: one caller follows one attempt's
    # stream (retries still re-dispatch a failed one)
    no_hedge: bool = field(default=False, repr=False, compare=False)

    def __post_init__(self) -> None:
        if not self.request_id:
            self.request_id = uuid.uuid4().hex


@dataclass
class GatewayResult:
    request_id: str
    status: str                          # ok | rejected | error | timeout
    tokens: List[int] = field(default_factory=list)
    replica: str = ""
    error: str = ""
    attempts: int = 0
    hedged: bool = False
    queue_wait_s: float = 0.0
    total_s: float = 0.0


class PendingRequest:
    """Caller-side handle: resolves exactly once."""

    def __init__(self, request_id: str) -> None:
        self.request_id = request_id
        self._done = threading.Event()
        self._result: Optional[GatewayResult] = None
        self._trace = None  # root SpanCtx; closed by Gateway._record
        self._tenant = ""   # stamped by submit; keys the quota release

    def _resolve(self, result: GatewayResult) -> None:
        self._result = result
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self) -> Optional[GatewayResult]:
        return self._result


class StreamRelay:
    """Token-prefix-dedup fan-in for streaming delivery: every attempt's
    ``on_tokens`` deltas flow through one relay that forwards each token
    INDEX exactly once, whichever attempt supplies it first.

    This is what makes hedged STREAMING sound: greedy decode is
    deterministic, so a hedge twin's stream is byte-identical to the
    primary's — the relay tracks a global emitted watermark and slices
    every delta against it, so the caller's stream never duplicates a
    token and never gaps, no matter how the two streams interleave (or
    which one wins the terminal result).  The same watermark is what a
    hedge/retry ships to the replica (``resume_watermark``) so the twin
    fast-forwards its EMISSION past tokens the caller already has; an
    attempt that was fast-forwarded declares its start offset in
    ``attempt.stream_base``.

    ``dedup=False`` is the UNPINNED-sampled mode (temperature > 0 with
    no request seed: replicas do NOT emit identical streams, so mixing
    them would be incoherent): only the first attempt to deliver a
    delta may stream — the pre-tier behavior — and the terminal result
    stays authoritative.  Seed-PINNED sampled requests keep
    ``dedup=True``: position-keyed sample keys make every replica's
    stream byte-identical, the same invariant greedy gets for free.
    """

    def __init__(self, metrics: Optional[Metrics] = None,
                 dedup: bool = True, base: int = 0) -> None:
        import queue as _queue

        self.metrics = metrics
        self.dedup = dedup
        self.q: "_queue.Queue[list]" = _queue.Queue()
        self._lock = threading.Lock()
        # keyed by the ATTEMPT OBJECT (identity hash), not id(): keeping
        # the attempt referenced means a dead attempt's address can
        # never be recycled into a new attempt that would inherit its
        # position instead of its own stream_base.  Bounded by the
        # attempts of one request.
        self._positions: Dict[object, int] = {}  # attempt -> abs end
        # ``base``: tokens the CALLER already holds — a stream resumed
        # on a sibling gateway after its home died starts its relay at
        # the caller-supplied resume watermark, so the dispatcher ships
        # it down the wire and dedup skips the delivered prefix
        self._emitted = int(base)
        self._pinned: Optional[object] = None  # dedup=False: the streamer

    def on_tokens(self, attempt, delta) -> None:
        """Deltas enqueue UNDER the lock: the dedup watermark decides
        order, so the put must be atomic with it — two attempts racing
        the queue after releasing the lock could deliver the caller's
        stream out of order despite each token arriving exactly once."""
        if not delta:
            return
        with self._lock:
            if not self.dedup:
                if self._pinned is None:
                    self._pinned = attempt
                if self._pinned is not attempt:
                    return
                self._emitted += len(delta)
                self.q.put(list(delta))
                return
            start = self._positions.get(
                attempt, int(getattr(attempt, "stream_base", 0) or 0)
            )
            end = start + len(delta)
            self._positions[attempt] = end
            if end <= self._emitted:
                # a slower twin re-delivering tokens the caller has:
                # drop, count — this is the "zero double-served" half
                if self.metrics is not None:
                    self.metrics.inc(
                        "gateway_stream_dedup_tokens_total", len(delta)
                    )
                return
            fresh = list(delta[max(self._emitted - start, 0):])
            dropped = len(delta) - len(fresh)
            if dropped and self.metrics is not None:
                self.metrics.inc(
                    "gateway_stream_dedup_tokens_total", dropped
                )
            self._emitted = end
            self.q.put(fresh)

    def emitted(self) -> int:
        """Tokens delivered so far — the resume watermark a hedge, a
        retry, or a sibling-gateway failover carries so the replica
        fast-forwards emission past them."""
        with self._lock:
            return self._emitted

    def drain(self) -> List[int]:
        """Everything queued right now (non-blocking), flattened."""
        import queue as _queue

        out: List[int] = []
        while True:
            try:
                out.extend(self.q.get_nowait())
            except _queue.Empty:
                return out


class Gateway:
    def __init__(
        self,
        registry: ReplicaRegistry,
        client: ReplicaClient,
        router: Optional[Router] = None,
        queue: Optional[AdmissionQueue] = None,
        policy: Optional[FailoverPolicy] = None,
        metrics: Optional[Metrics] = None,
        dispatchers: int = 4,
        max_results: int = 65536,
        tracer: Optional[Tracer] = None,
        trace: bool = True,
        session_store: Optional[SessionKVStore] = None,
        prefix_tier=None,
        gateway_id: str = "",
    ) -> None:
        self.registry = registry
        self.client = client
        self.queue = queue or AdmissionQueue()
        self.metrics = metrics or default_metrics
        # the tier's name for this instance (rides trace roots and drain
        # stats); empty for a standalone gateway
        self.gateway_id = gateway_id
        # request tracing is ON by default (bounded ring, a handful of
        # dict ops per request): every request yields one span tree —
        # admission_wait / route / dispatch / replica-side serve phases
        # — served at /debug/trace.  trace=False opts out entirely.
        self.tracer = tracer if tracer is not None else (
            Tracer() if trace else None
        )
        # a metrics-capable router (SessionAffinityRouter's repin
        # counter) that wasn't given its own registry reports into the
        # gateway's, so /metrics shows KV-loss re-pins next to the
        # serve_* histograms the replica batchers feed
        if router is not None and getattr(router, "metrics", False) is None:
            router.metrics = self.metrics
        # sealed-session KV insurance: completed sessionful turns are
        # recorded (and, when the serving replica seals decode pages,
        # eagerly exported ASYNCHRONOUSLY) so a later replica death or
        # drain re-pins the session WITH its KV — the dispatcher
        # restores the payload into the new target before the turn-2
        # attempt opens.  A tier passes ONE shared store into all its
        # gateways; a multi-process deployment passes a store backed by
        # the external StoreServer (gateway/sessionstore.py), which is
        # what makes the insurance survive THIS pod's death.
        self.session_store = session_store or SessionKVStore(
            metrics=self.metrics
        )
        self._seals_cache: Dict[str, bool] = {}
        # fleet-wide shared-prefix tier (gateway/prefixtier.PrefixTier):
        # sealed chains from ok completions publish to the store under
        # their content hash, and cold dispatch targets import the
        # longest stored prefix before prefill.  Optional — None keeps
        # the pre-tier behavior exactly.  A GatewayTier passes ONE
        # shared tier into all its gateways, same as the session store.
        self.prefix_tier = prefix_tier
        if prefix_tier is not None and getattr(
            prefix_tier, "metrics", False
        ) is None:
            prefix_tier.metrics = self.metrics
        self.dispatcher = Dispatcher(
            client,
            router or LeastOutstandingRouter(),
            policy or FailoverPolicy(),
            metrics=self.metrics,
            session_store=self.session_store,
            prefix_tier=self.prefix_tier,
        )
        self.n_dispatchers = dispatchers
        self._stop = threading.Event()
        # DRAINING (graceful shutdown, SIGTERM): new admissions refuse
        # with the retryable shutdown error, in-flight work finishes,
        # /readyz reports 503 so the load balancer stops sending —
        # distinct from _stop (the dispatchers keep running until the
        # drain completes)
        self._draining = threading.Event()
        # overload BROWNOUT ladder (the fleet controller's degrade-don't-
        # fail surface, applied when capacity cannot arrive in time):
        #   0 none; 1 hedging disabled; 2 + speculation shrunk;
        #   3 + lowest-priority/over-quota tenants shed with retryable
        #   429s, every shed counted in gateway_shed_total{reason}
        self._brownout_level = 0
        self._shed_tenants: frozenset = frozenset()
        # tenant -> outstanding (submitted, not yet terminal): the
        # over-quota shed rule's input, maintained under _lock
        self._tenant_outstanding: Dict[str, int] = {}
        self._started = False
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._pending: Dict[str, PendingRequest] = {}
        # requests currently INSIDE a dispatcher thread (dequeued, not
        # yet terminal), keyed by id: kill() sets their abort events so
        # a dying gateway's in-flight attempts cancel wire-level — the
        # in-process analog of a crashed pod's sockets closing
        self._live_requests: Dict[str, GatewayRequest] = {}
        # FIFO-bounded: a long-lived gateway must not retain every result
        # (token lists included) for the life of the process
        self._results: "OrderedDict[str, GatewayResult]" = OrderedDict()
        self.max_results = max_results
        self._in_flight = 0
        # drain accounting: submitted counts BEFORE submit() returns and
        # resolved counts at _record(), so "submitted == resolved" has no
        # window where a dequeued-but-uncounted request looks quiescent
        # (queue depth and _in_flight alone have exactly that gap)
        self._n_submitted = 0
        self._n_resolved = 0
        # per-replica completed-request counts (the routing-balance
        # acceptance check reads this)
        self.completed_by_replica: Dict[str, int] = {}
        registry.subscribe(self._on_live_change)
        # seed the gauge from the current set: the subscription only fires
        # on CHANGE, and the registry may already be refreshed
        self._on_live_change(registry.live_keys())

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._started = True
        for i in range(self.n_dispatchers):
            t = threading.Thread(
                target=self._dispatch_loop, name=f"gw-dispatch-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        self.registry.unsubscribe(self._on_live_change)
        for t in self._threads:
            t.join(timeout=5.0)
        # still-queued requests and any stragglers a wedged dispatcher
        # left behind resolve with an explicit shutdown error — a client
        # blocked in submit_and_wait must not wait out its whole deadline
        # for an answer that can never come.  (_record drops duplicates,
        # so racing a late dispatcher result is safe.)
        while True:
            request = self.queue.get(timeout=0)
            if request is None:
                break
            self._record(GatewayResult(
                request.request_id, "error", error="gateway shutting down",
            ))
        with self._lock:
            leftovers = list(self._pending)
        for rid in leftovers:
            self._record(GatewayResult(
                rid, "error", error="gateway shutting down",
            ))

    def kill(self) -> None:
        """Abrupt death — the tier's chaos surface, distinct from the
        graceful ``stop()``: no joins, no waiting.  Every in-flight
        request's abort event fires (the dispatcher cancels its attempts
        WIRE-LEVEL, so replicas free the sequences' pages — exactly what
        a crashed gateway pod's closed sockets would cause via the
        replica's disconnect⇒cancel path), queued and pending requests
        resolve with an explicit "gateway died" error (the tier client's
        retry-on-a-sibling trigger), and the registry subscription
        detaches so the corpse stops observing the shared live set."""
        self._stop.set()
        self.queue.close()
        self.registry.unsubscribe(self._on_live_change)
        with self._lock:
            live = list(self._live_requests.values())
        for request in live:
            abort = getattr(request, "abort", None)
            if abort is not None:
                abort.set()
        while True:
            request = self.queue.get(timeout=0)
            if request is None:
                break
            self._record(GatewayResult(
                request.request_id, "error", error="gateway died",
            ))
        with self._lock:
            leftovers = list(self._pending)
        for rid in leftovers:
            self._record(GatewayResult(
                rid, "error", error="gateway died",
            ))

    @property
    def alive(self) -> bool:
        return not self._stop.is_set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def accepting(self) -> bool:
        """Is this instance willing to take NEW admissions?  The
        per-instance half of /readyz: alive, dispatcher pool started,
        not draining.  (The other half — ≥1 routable replica and a wired
        data plane — is the server's to check.)"""
        return self.alive and self._started and not self.draining

    def begin_drain(self) -> None:
        """Graceful shutdown, step 1 (SIGTERM): stop accepting new
        admissions (submit refuses with the RETRYABLE shutdown error, so
        a tier client or load balancer re-routes to a sibling) while
        in-flight requests — live streams included — run to completion.
        The caller then waits on ``drain()`` and calls ``stop()``."""
        self._draining.set()

    # -- overload brownout (the controller's degrade surface) --------------
    @property
    def brownout_level(self) -> int:
        return self._brownout_level

    def set_brownout(self, level: int,
                     shed_tenants=frozenset()) -> None:
        """Apply one rung of the overload brownout ladder.  Level 1
        disables hedged dispatch (a browned-out fleet must not amplify
        its own load 2x); level 2 additionally shrinks speculation on
        replicas that support a live cap (the verify window's extra
        budget rows go back to admissions; greedy output is lossless
        for ANY draft, so tokens are unchanged); level 3 additionally
        sheds lowest-priority (``shed_tenants``) and over-quota tenants
        at ADMISSION with retryable 429s — every shed counted and
        auditable in ``gateway_shed_total{reason="brownout"}``.  Level
        0 restores everything.  Idempotent; the controller re-reads
        ``brownout_level`` after a restart."""
        level = max(0, min(3, int(level)))
        prev = self._brownout_level
        self._brownout_level = level
        self._shed_tenants = frozenset(shed_tenants)
        self.dispatcher.hedge_disabled = level >= 1
        if (level >= 2) != (prev >= 2):
            shrink = getattr(self.client, "set_speculation", None)
            if shrink is not None:
                try:
                    shrink(1 if level >= 2 else None)
                except Exception:  # noqa: BLE001 - advisory knob
                    log.exception("speculation shrink failed")
        self.metrics.set_gauge("gateway_brownout_level", level)

    def set_disaggregation(self, enabled: bool) -> None:
        """Collapse (or restore) prefill/decode disaggregation — the
        brownout ladder's handoff rung.  Disabled, the dispatcher still
        reacts to sealed announcements (a parked sequence must decode
        SOMEWHERE) but resolves them locally on the prefill replica
        instead of shipping KV to a decode peer; the controller flips
        the replicas' roles back to flex alongside, so new admissions
        stop sealing at all."""
        self.dispatcher.disaggregation = bool(enabled)

    def _shed_locked(self, request: GatewayRequest) -> bool:
        """Level-3 admission shed (called under _lock, BEFORE this
        request's own outstanding count lands): lowest-priority tenants
        always; otherwise a tenant already holding at least its fair
        share of the queue's capacity (capacity // active tenants,
        floor 1) is over quota — the hog pays for the brownout, the
        light tenants keep flowing."""
        tenant = request.tenant
        if not tenant:
            return False
        if tenant in self._shed_tenants:
            return True
        mine = self._tenant_outstanding.get(tenant, 0)
        if mine == 0:
            return False
        quota = max(
            1, self.queue.capacity // max(1, len(self._tenant_outstanding))
        )
        return mine >= quota

    # -- submission (the HTTP handler's surface) ---------------------------
    def submit(self, request: GatewayRequest) -> PendingRequest:
        """Admit or refuse NOW.  Refusal still resolves the handle — with
        an explicit "rejected" result — so callers have ONE code path."""
        pending = PendingRequest(request.request_id)
        with self._lock:
            if (request.request_id in self._pending
                    or request.request_id in self._results):
                raise ValueError(
                    f"duplicate request_id {request.request_id}"
                )
            # brownout shed decided BEFORE this request's own count
            # lands (the quota judges what the tenant already holds)
            shed = (self._brownout_level >= 3
                    and self._shed_locked(request))
            self._pending[request.request_id] = pending
            self._n_submitted += 1
            pending._tenant = request.tenant
            if request.tenant:
                self._tenant_outstanding[request.tenant] = (
                    self._tenant_outstanding.get(request.tenant, 0) + 1
                )
        if self.tracer is not None:
            attrs = dict(request_id=request.request_id,
                         tenant=request.tenant)
            if self.gateway_id:
                attrs["gateway"] = self.gateway_id
            request.trace = self.tracer.start_trace(
                "gateway_request", **attrs
            )
            pending._trace = request.trace
        if self._draining.is_set():
            # graceful shutdown: refuse with the RETRYABLE death error
            # (the tier client's re-submit trigger, and the analog of a
            # pod whose /readyz already went 503 but that a racing
            # client still reached) — in-flight work keeps serving
            self.metrics.inc("gateway_requests_total", outcome="error")
            self._record(GatewayResult(
                request.request_id, "error",
                error="gateway shutting down (draining)",
            ))
            return pending
        if shed:
            # brownout load-shed: an explicit, RETRYABLE 429 — the
            # degrade-don't-fail half of the controller's contract when
            # capacity cannot arrive in time.  Counted and auditable.
            self.metrics.inc("gateway_requests_total", outcome="rejected")
            self.metrics.inc("gateway_shed_total", reason="brownout")
            self._record(GatewayResult(
                request.request_id, "rejected",
                error="brownout: lowest-priority/over-quota traffic "
                "shed; retry with backoff",
            ))
            return pending
        request.enqueued_at = time.monotonic()
        try:
            self.queue.put(request)
        except QueueFull as e:
            self.metrics.inc("gateway_requests_total", outcome="rejected")
            self._record(GatewayResult(
                request.request_id, "rejected", error=str(e),
            ))
            return pending
        except QueueClosed as e:
            # NOT backpressure: the queue only closes when this gateway
            # is dying — a submit racing kill()/stop() must resolve with
            # the RETRYABLE death error so a tier client re-submits on a
            # sibling instead of surfacing a spurious rejection
            self.metrics.inc("gateway_requests_total", outcome="error")
            self._record(GatewayResult(
                request.request_id, "error", error=f"gateway died: {e}",
            ))
            return pending
        self.metrics.set_gauge("gateway_queue_depth", self.queue.depth())
        return pending

    def submit_and_wait(self, request: GatewayRequest,
                        timeout: Optional[float] = None) -> GatewayResult:
        pending = self.submit(request)
        deadline = timeout or (
            (request.deadline_s or self.dispatcher.policy.deadline_s) + 5.0
        )
        if not pending.wait(deadline):
            return GatewayResult(
                request.request_id, "timeout",
                error="gateway did not resolve in time",
            )
        return pending.result()

    # -- dispatch ----------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            request = self.queue.get(timeout=0.05)
            self.metrics.set_gauge("gateway_queue_depth", self.queue.depth())
            if request is None:
                continue
            with self._lock:
                self._in_flight += 1
                self._live_requests[request.request_id] = request
            try:
                started = time.monotonic()
                queue_wait = started - request.enqueued_at
                self.metrics.observe("gateway_queue_wait_seconds", queue_wait)
                # ROUTABLE, not live: a DRAINING replica keeps serving
                # its in-flight work but takes no new admissions
                outcome = self.dispatcher.dispatch(
                    request, self.registry.routable
                )
                total = time.monotonic() - request.enqueued_at
                if outcome.status == "ok" and request.session:
                    self._record_session(request, outcome)
                if outcome.status == "ok" and (
                    self.prefix_tier is not None and outcome.replica
                ):
                    # ANY ok completion may have sealed a publishable
                    # chain (sessionless agent scaffolds included) —
                    # queue it off the result path; the tier dedups
                    self.prefix_tier.publish_async(
                        self.client, outcome.replica,
                        list(request.prompt) + list(outcome.tokens),
                    )
                if outcome.status == "ok":
                    # both the unlabeled aggregate (the FleetObserver's
                    # window diffs read it) AND the role-split series:
                    # "disaggregated" = prefilled on one replica, decoded
                    # on another; the handoff-fallback path counts as
                    # colocated (one replica did all the work)
                    role = (
                        "disaggregated" if outcome.handed_off
                        else "colocated"
                    )
                    self.metrics.observe("gateway_ttft_seconds", total)
                    self.metrics.observe(
                        "gateway_ttft_seconds", total, role=role
                    )
                    if outcome.tokens:
                        itl = total / max(1, len(outcome.tokens))
                        self.metrics.observe("gateway_itl_seconds", itl)
                        self.metrics.observe(
                            "gateway_itl_seconds", itl, role=role
                        )
                self.metrics.inc(
                    "gateway_requests_total", outcome=outcome.status
                )
                self._record(GatewayResult(
                    request.request_id, outcome.status,
                    tokens=outcome.tokens, replica=outcome.replica,
                    error=outcome.error, attempts=outcome.attempts,
                    hedged=outcome.hedged, queue_wait_s=queue_wait,
                    total_s=total,
                ))
            except Exception as e:  # noqa: BLE001 - dispatcher must survive
                log.exception("dispatch failed for %s", request.request_id)
                self._record(GatewayResult(
                    request.request_id, "error",
                    error=f"internal dispatch error: {e}",
                ))
            finally:
                with self._lock:
                    self._in_flight -= 1
                    self._live_requests.pop(request.request_id, None)

    def _record_session(self, request: GatewayRequest, outcome) -> None:
        """A sessionful turn completed ok: record the session's home +
        stream, and — when that replica actually seals decode pages —
        queue a sealed-export capture (the failover insurance premium,
        paid while the replica is alive).  The capture runs
        ASYNCHRONOUSLY off the result path (bounded queue, drop-oldest
        — capture is insurance, never admission-blocking); the tiny
        record itself is synchronous so the very next turn's restore
        sees the session's home.  Best-effort and gated per replica so
        SimBatcher/policy-off lanes never pay a round-trip."""
        try:
            self.session_store.record(
                request.session, outcome.replica,
                list(request.prompt) + list(outcome.tokens),
            )
            seals = self._seals_cache.get(outcome.replica)
            if seals is None:
                seals = bool(self.client.seals_decode(outcome.replica))
                self._seals_cache[outcome.replica] = seals
            if seals:
                self.session_store.capture_async(
                    self.client, request.session
                )
        except Exception:  # noqa: BLE001 - insurance must never fail serving
            log.exception("sealed-session capture failed")

    # -- replica lifecycle (DRAINING → released) ---------------------------
    def drain_replica(self, key: str, migrate: bool = True) -> dict:
        """The graceful half of the replica lifecycle: mark the replica
        DRAINING (routing stops sending new admissions the same cycle),
        capture sealed exports for the sessions it last served, migrate
        its live in-flight sequences to healthy replicas, and unpin its
        sessions (a planned move, not a KV loss — their next turn
        restores from the captured exports).  Returns drain stats; the
        caller releases the replica (deletes the pod) afterwards.
        Everything degrades gracefully: a sequence that cannot migrate
        keeps serving on the draining replica until it finishes or the
        release kills it (normal failover then retries it cold)."""
        self.registry.set_draining(key, True)
        self.metrics.inc("gateway_replica_drains_total")
        captured = 0
        if self._seals_cache.get(key) or self.client.seals_decode(key):
            for session in self.session_store.sessions_on(key):
                if self.session_store.capture(self.client, session):
                    captured += 1
        migrated = failed = 0
        if migrate:
            targets = [
                r for r in self.registry.routable() if r.key != key
            ]
            for attempt in self.client.inflight_on(key):
                if attempt.done or not targets:
                    continue
                request = attempt.request
                if request is None:
                    failed += 1
                    continue
                target = min(
                    targets,
                    key=lambda r: self.dispatcher.outstanding.get(r.key, 0),
                )
                if self.client.migrate(attempt, request, target.key):
                    migrated += 1
                else:
                    failed += 1
        # planned unpin: the affinity router's next pick re-pins by load
        # and the restored export keeps the KV warm
        self.session_store.mark_lost(key)
        if self.prefix_tier is not None:
            self.prefix_tier.forget_replica(key)
        forget = getattr(self.dispatcher.router, "forget_replica", None)
        if forget is not None:
            forget(key)
        return {
            "replica": key, "migrated": migrated, "failed": failed,
            "captured": captured,
        }

    # -- exactly-once delivery --------------------------------------------
    def _record(self, result: GatewayResult) -> None:
        with self._lock:
            if result.request_id in self._results:
                # a hedge race or a stale retry produced a second terminal
                # result: count it, DROP it — the first answer stands
                self.metrics.inc("gateway_duplicate_results_total")
                return
            self._results[result.request_id] = result
            while len(self._results) > self.max_results:
                self._results.popitem(last=False)
            pending = self._pending.pop(result.request_id, None)
            self._n_resolved += 1
            if pending is not None and pending._tenant:
                n = self._tenant_outstanding.get(pending._tenant, 1) - 1
                if n <= 0:
                    self._tenant_outstanding.pop(pending._tenant, None)
                else:
                    self._tenant_outstanding[pending._tenant] = n
            if result.status == "ok" and result.replica:
                self.completed_by_replica[result.replica] = (
                    self.completed_by_replica.get(result.replica, 0) + 1
                )
        if pending is not None:
            if pending._trace is not None:
                # the root closes with the terminal result; a hedge
                # loser's serve spans may still be draining — the trace
                # completes once they close (tracing.Tracer's
                # completion rule), dispatch spans carry overhang_ok
                pending._trace.end(
                    status=result.status, attempts=result.attempts,
                    replica=result.replica,
                )
            pending._resolve(result)

    # -- views -------------------------------------------------------------
    def results(self) -> Dict[str, GatewayResult]:
        with self._lock:
            return dict(self._results)

    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for quiescence: every submitted request has a terminal
        result.  (Counter equality, not queue-depth + in-flight: those two
        have a window where a dequeued request is counted by neither.)"""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._n_submitted == self._n_resolved:
                    return True
            time.sleep(0.01)
        return False

    def _on_live_change(self, live) -> None:
        self.metrics.set_gauge("gateway_live_replicas", len(live))
        # a replica leaving the live set strands its sessions' KV: mark
        # them restorable (the dispatcher imports any captured sealed
        # export into the re-pin target), and forget its sealing policy
        # (a revived pod may come back configured differently)
        self.session_store.sync_live(live)
        if self.prefix_tier is not None:
            self.prefix_tier.sync_live(live)
        for key in [k for k in self._seals_cache if k not in live]:
            self._seals_cache.pop(key, None)
