// Native TPU discovery shim — the TPU-native equivalent of the reference's
// cgo→NVML binding (SURVEY.md §2 #7, §2.1): the one native component the
// reference had, rebuilt for TPU hosts.  Where NVML answered "how many GPUs,
// what state are they in", this library answers the same for TPU chips from
// the three host-level sources a (GKE/GCE) TPU VM exposes:
//
//   1. devfs:   /dev/accel<N> (TPU VM runtime) or /dev/vfio/<N> device nodes
//   2. libtpu:  dlopen("libtpu.so"), presence of the PJRT entry symbol
//   3. env:     TPU_ACCELERATOR_TYPE / TPU_TOPOLOGY / TPU_WORKER_ID
//
// Exposed as a plain C ABI consumed from Python via ctypes
// (kubegpu_tpu/plugins/native.py) — no pybind11 dependency.  All inputs are
// parameterized (devfs root, env already read by the caller) so the library
// itself is unit-testable against a fabricated /dev tree.
//
// Health semantics: a chip index whose device node exists but is not
// readable+writable by this process is reported present-but-unhealthy;
// the Python layer folds that into the advertised capacity so dead chips
// drop out of the cluster's allocatable set (SURVEY.md §5.3).

#include <dirent.h>
#include <dlfcn.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <climits>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {

#define TPU_DISCOVERY_MAX_CHIPS 256
#define TPU_DISCOVERY_PATH_MAX 128

typedef struct {
  int index;                          // chip index parsed from the node name
  char path[TPU_DISCOVERY_PATH_MAX];  // absolute device-node path
  int accessible;                     // 1 = R+W openable by this process
} tpu_chip_node;

typedef struct {
  int chip_count;
  tpu_chip_node chips[TPU_DISCOVERY_MAX_CHIPS];
  int libtpu_present;   // dlopen(libtpu.so) succeeded
  int libtpu_has_pjrt;  // ...and it exports GetPjrtApi
  char libtpu_path[TPU_DISCOVERY_PATH_MAX];
} tpu_host_probe;

const char* tpu_discovery_version(void) { return "kubegpu-tpu-discovery/1"; }

// ABI handshake: callers must verify this equals sizeof their struct before
// passing one in (the NVML versioned-struct pattern) — a stale library with
// different MAX_CHIPS/PATH_MAX would otherwise overrun the caller's buffer.
int tpu_discovery_probe_size(void) { return (int)sizeof(tpu_host_probe); }

namespace {

// accel nodes carry their chip index in the name ("accel3" -> 3); vfio
// nodes are numbered but the number is a group id, not a chip id — they
// are ranked numerically and re-indexed densely by the caller's policy.
bool parse_index(const char* name, const char* prefix, int* out) {
  size_t plen = std::strlen(prefix);
  if (std::strncmp(name, prefix, plen) != 0) return false;
  const char* digits = name + plen;
  if (*digits == '\0') return false;
  char* end = nullptr;
  long v = std::strtol(digits, &end, 10);
  if (*end != '\0' || v < 0 || v > INT_MAX) return false;
  *out = static_cast<int>(v);
  return true;
}

void scan_dir(const std::string& dir, const char* prefix, bool index_is_chip_id,
              std::vector<tpu_chip_node>* out) {
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return;
  std::vector<tpu_chip_node> found;
  struct dirent* ent;
  while ((ent = readdir(d)) != nullptr) {
    int idx = -1;
    if (!parse_index(ent->d_name, prefix, &idx)) continue;
    std::string path = dir + "/" + ent->d_name;
    struct stat st{};
    if (stat(path.c_str(), &st) != 0) continue;
    tpu_chip_node node{};
    node.index = idx;
    std::snprintf(node.path, sizeof(node.path), "%s", path.c_str());
    node.accessible = access(path.c_str(), R_OK | W_OK) == 0 ? 1 : 0;
    found.push_back(node);
  }
  closedir(d);
  // deterministic ascending order regardless of readdir order
  for (size_t i = 0; i < found.size(); ++i)
    for (size_t j = i + 1; j < found.size(); ++j)
      if (found[j].index < found[i].index) std::swap(found[i], found[j]);
  if (!index_is_chip_id)  // vfio: dense re-index after sorting
    for (size_t i = 0; i < found.size(); ++i) found[i].index = static_cast<int>(i);
  out->insert(out->end(), found.begin(), found.end());
}

}  // namespace

// Probe device nodes under `devfs_root` (e.g. "/dev"); fills `out`.
// probe_libtpu != 0 additionally dlopens libtpu.so to report its presence —
// costly (libtpu is huge), so callers on hot paths pass 0.
// Returns 0 on success (including zero chips — a CPU host), -1 on bad args.
int tpu_discovery_probe(const char* devfs_root, int probe_libtpu,
                        tpu_host_probe* out) {
  if (devfs_root == nullptr || out == nullptr) return -1;
  std::memset(out, 0, sizeof(*out));

  std::vector<tpu_chip_node> nodes;
  scan_dir(devfs_root, "accel", /*index_is_chip_id=*/true, &nodes);
  if (nodes.empty())  // TPU VM runtime absent: fall back to vfio passthrough
    scan_dir(std::string(devfs_root) + "/vfio", "", /*index_is_chip_id=*/false,
             &nodes);
  for (const auto& n : nodes) {
    if (out->chip_count >= TPU_DISCOVERY_MAX_CHIPS) break;
    out->chips[out->chip_count++] = n;
  }

  if (probe_libtpu == 0) return 0;
  static const char* kLibtpuNames[] = {"libtpu.so", "libtpu.so.1"};
  for (const char* name : kLibtpuNames) {
    void* h = dlopen(name, RTLD_LAZY | RTLD_LOCAL);
    if (h == nullptr) continue;
    out->libtpu_present = 1;
    out->libtpu_has_pjrt = dlsym(h, "GetPjrtApi") != nullptr ? 1 : 0;
    std::snprintf(out->libtpu_path, sizeof(out->libtpu_path), "%s", name);
    dlclose(h);
    break;
  }
  return 0;
}

}  // extern "C"
