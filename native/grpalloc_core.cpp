// Native allocator hot loop — the C++ twin of grpalloc's rectangle scan
// (kubegpu_tpu/grpalloc/allocator.py fit_gang candidates; SURVEY.md §3.1
// marks this walk as the scheduler's hot loop).  Semantics are DEFINED by
// the Python code in kubegpu_tpu/grpalloc/scoring.py +
// kubegpu_tpu/types/topology.py; this file replicates them operation-for-
// operation (same IEEE-double arithmetic order, same tie-breaks) and is
// parity-tested against the Python in tests/test_native_grpalloc.py — any
// divergence is a bug HERE, not there.
//
// Plain C ABI over flat arrays (no structs, so no size handshake needed),
// consumed from Python via ctypes (kubegpu_tpu/grpalloc/native_core.py).
// Cells are row-major flat indices into the mesh; masks are uint8[volume].

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr double W_CONTIG = 60.0;
constexpr double W_ASPECT = 15.0;
constexpr double W_FRAG = 25.0;
constexpr int MAX_NDIMS = 3;

struct Geometry {
  int ndims;
  int shape[MAX_NDIMS];
  bool wrap[MAX_NDIMS];
  int volume;
};

inline void unflatten(const Geometry& g, int idx, int* c) {
  for (int d = g.ndims - 1; d >= 0; --d) {
    c[d] = idx % g.shape[d];
    idx /= g.shape[d];
  }
}

inline int flatten(const Geometry& g, const int* c) {
  int idx = 0;
  for (int d = 0; d < g.ndims; ++d) idx = idx * g.shape[d] + c[d];
  return idx;
}

// factor_shapes(n, ndims): all ndims-tuples with product n, sorted unique
// (topology.py:factor_shapes — recursion emits sorted output after dedup).
void factor_shapes(int n, int ndims, std::vector<std::vector<int>>* out) {
  if (ndims == 1) {
    out->push_back({n});
    return;
  }
  for (int first = 1; first <= n; ++first) {
    if (n % first != 0) continue;
    std::vector<std::vector<int>> rest;
    factor_shapes(n / first, ndims - 1, &rest);
    for (auto& r : rest) {
      std::vector<int> s;
      s.push_back(first);
      s.insert(s.end(), r.begin(), r.end());
      out->push_back(std::move(s));
    }
  }
  std::sort(out->begin(), out->end());
  out->erase(std::unique(out->begin(), out->end()), out->end());
}

// coords_bounding_box over a set of cells (topology.py).
void bounding_shape(const Geometry& g, const std::vector<int>& cells,
                    int* out_shape) {
  int lo[MAX_NDIMS], hi[MAX_NDIMS], c[MAX_NDIMS];
  for (int d = 0; d < g.ndims; ++d) {
    lo[d] = INT32_MAX;
    hi[d] = INT32_MIN;
  }
  for (int cell : cells) {
    unflatten(g, cell, c);
    for (int d = 0; d < g.ndims; ++d) {
      lo[d] = std::min(lo[d], c[d]);
      hi[d] = std::max(hi[d], c[d]);
    }
  }
  for (int d = 0; d < g.ndims; ++d) out_shape[d] = hi[d] - lo[d] + 1;
}

// scoring.py:aspect_score — min/max of the bounding-box extents.
double aspect_score(const Geometry& g, const std::vector<int>& cells) {
  if (cells.empty()) return 0.0;
  int shape[MAX_NDIMS];
  bounding_shape(g, cells, shape);
  int mn = shape[0], mx = shape[0];
  for (int d = 1; d < g.ndims; ++d) {
    mn = std::min(mn, shape[d]);
    mx = std::max(mx, shape[d]);
  }
  return static_cast<double>(mn) / static_cast<double>(mx);
}

// scoring.py:frag_score — 1 - exposed free perimeter / max possible.
// Neighbor iteration mirrors scoring.py:neighbors exactly: per dim, ±1,
// in-range, else wrapped only when wrap[d] and shape[d] > 2.
double frag_score(const Geometry& g, const std::vector<int>& cells,
                  const uint8_t* free_mask) {
  if (cells.empty()) return 0.0;
  std::vector<uint8_t> alloc(g.volume, 0);
  for (int cell : cells) alloc[cell] = 1;
  int exposed = 0;
  int c[MAX_NDIMS];
  for (int cell : cells) {
    unflatten(g, cell, c);
    for (int d = 0; d < g.ndims; ++d) {
      for (int step = -1; step <= 1; step += 2) {
        int v = c[d] + step;
        int nb;
        if (v >= 0 && v < g.shape[d]) {
          int saved = c[d];
          c[d] = v;
          nb = flatten(g, c);
          c[d] = saved;
        } else if (g.wrap[d] && g.shape[d] > 2) {
          int saved = c[d];
          c[d] = ((v % g.shape[d]) + g.shape[d]) % g.shape[d];
          nb = flatten(g, c);
          c[d] = saved;
        } else {
          continue;
        }
        // "in remaining_free" = free minus the allocation itself
        if (free_mask[nb] && !alloc[nb]) ++exposed;
      }
    }
  }
  double max_exposed =
      2.0 * static_cast<double>(g.ndims) * static_cast<double>(cells.size());
  return 1.0 - static_cast<double>(exposed) / max_exposed;
}

// topology.py:is_contiguous_submesh.  Rectangle candidates from the
// enumeration are contiguous by construction; this generic form also
// serves the exported scoring entry point.
bool is_contiguous(const Geometry& g, const std::vector<int>& sorted_cells);

// scoring.py:placement_score — same term order, same weights.
double placement_score(const Geometry& g, const std::vector<int>& sorted_cells,
                       const uint8_t* free_mask, bool known_contiguous) {
  if (sorted_cells.empty()) return 0.0;
  double contig;
  if (known_contiguous || is_contiguous(g, sorted_cells)) {
    contig = 1.0;
  } else {
    int shape[MAX_NDIMS];
    bounding_shape(g, sorted_cells, shape);
    int vol = 1;
    for (int d = 0; d < g.ndims; ++d) vol *= shape[d];
    contig = static_cast<double>(sorted_cells.size()) / static_cast<double>(vol);
  }
  return W_CONTIG * contig + W_ASPECT * aspect_score(g, sorted_cells) +
         W_FRAG * frag_score(g, sorted_cells, free_mask);
}

// Emit every rectangle of exactly n cells (topology.py:enumerate_rectangles
// order: factor shapes sorted, then row-major origins), as sorted flat cells.
// visit returns false to stop early (buffer full).
template <typename Visit>
void enumerate_rects(const Geometry& g, int n, Visit visit) {
  std::vector<std::vector<int>> shapes;
  factor_shapes(n, g.ndims, &shapes);
  for (const auto& shape : shapes) {
    bool fits = true;
    for (int d = 0; d < g.ndims; ++d)
      if (shape[d] > g.shape[d]) fits = false;
    if (!fits) continue;
    int ranges[MAX_NDIMS];
    for (int d = 0; d < g.ndims; ++d)
      ranges[d] = (g.wrap[d] && shape[d] < g.shape[d])
                      ? g.shape[d]
                      : g.shape[d] - shape[d] + 1;
    int origin[MAX_NDIMS] = {0, 0, 0};
    for (;;) {
      std::vector<int> cells;
      cells.reserve(n);
      int off[MAX_NDIMS] = {0, 0, 0};
      for (;;) {
        int c[MAX_NDIMS];
        for (int d = 0; d < g.ndims; ++d)
          c[d] = (origin[d] + off[d]) % g.shape[d];
        cells.push_back(flatten(g, c));
        int d = g.ndims - 1;
        while (d >= 0 && ++off[d] == shape[d]) off[d--] = 0;
        if (d < 0) break;
      }
      std::sort(cells.begin(), cells.end());
      if (!visit(cells)) return;
      int d = g.ndims - 1;
      while (d >= 0 && ++origin[d] == ranges[d]) origin[d--] = 0;
      if (d < 0) break;
    }
  }
}

bool is_contiguous(const Geometry& g, const std::vector<int>& sorted_cells) {
  if (sorted_cells.empty()) return false;
  bool any_wrap = false;
  for (int d = 0; d < g.ndims; ++d) any_wrap |= g.wrap[d];
  if (!any_wrap) {
    int shape[MAX_NDIMS];
    bounding_shape(g, sorted_cells, shape);
    int vol = 1;
    for (int d = 0; d < g.ndims; ++d) vol *= shape[d];
    return vol == static_cast<int>(sorted_cells.size());
  }
  bool found = false;
  enumerate_rects(g, static_cast<int>(sorted_cells.size()),
                  [&](const std::vector<int>& cells) {
                    if (cells == sorted_cells) {
                      found = true;
                      return false;
                    }
                    return true;
                  });
  return found;
}

bool init_geometry(const int* mesh_shape, const uint8_t* wrap, int ndims,
                   Geometry* g) {
  if (ndims < 1 || ndims > MAX_NDIMS) return false;
  g->ndims = ndims;
  g->volume = 1;
  for (int d = 0; d < ndims; ++d) {
    if (mesh_shape[d] < 1) return false;
    g->shape[d] = mesh_shape[d];
    g->wrap[d] = wrap[d] != 0;
    g->volume *= mesh_shape[d];
  }
  return true;
}

}  // namespace

extern "C" {

const char* grpalloc_core_version() { return "kubegpu-tpu-grpalloc/1"; }

// All FREE rectangles of exactly n_chips cells, scored and sorted the way
// fit_gang sorts its candidates: score descending, then lexicographic cell
// list.  out_cells receives count*n_chips flat indices (each candidate's
// cells ascending); out_scores receives count doubles.  Returns the
// candidate count, -1 on bad geometry, or -2 if max_out is too small.
int grpalloc_candidate_rectangles(const int* mesh_shape, const uint8_t* wrap,
                                  int ndims, const uint8_t* free_mask,
                                  int n_chips, int* out_cells,
                                  double* out_scores, int max_out) {
  Geometry g;
  if (!init_geometry(mesh_shape, wrap, ndims, &g) || n_chips < 1) return -1;
  std::vector<std::pair<double, std::vector<int>>> cands;
  enumerate_rects(g, n_chips, [&](const std::vector<int>& cells) {
    for (int cell : cells)
      if (!free_mask[cell]) return true;
    double s = placement_score(g, cells, free_mask, /*known_contiguous=*/true);
    cands.emplace_back(s, cells);
    return true;
  });
  std::sort(cands.begin(), cands.end(),
            [](const auto& a, const auto& b) {
              if (a.first != b.first) return a.first > b.first;
              return a.second < b.second;
            });
  if (static_cast<int>(cands.size()) > max_out) return -2;
  for (size_t i = 0; i < cands.size(); ++i) {
    out_scores[i] = cands[i].first;
    std::memcpy(out_cells + i * n_chips, cands[i].second.data(),
                sizeof(int) * n_chips);
  }
  return static_cast<int>(cands.size());
}

// placement_score for an arbitrary cell set (twin of scoring.py entry).
double grpalloc_score(const int* mesh_shape, const uint8_t* wrap, int ndims,
                      const uint8_t* free_mask, const int* alloc_cells,
                      int n_alloc) {
  Geometry g;
  if (!init_geometry(mesh_shape, wrap, ndims, &g) || n_alloc < 1) return -1.0;
  std::vector<int> cells(alloc_cells, alloc_cells + n_alloc);
  std::sort(cells.begin(), cells.end());
  for (int cell : cells)
    if (cell < 0 || cell >= g.volume) return -1.0;
  return placement_score(g, cells, free_mask, /*known_contiguous=*/false);
}

}  // extern "C"
